"""TPU-first dense linear algebra for the per-chunk workloads.

The reference runs its PCA workload as per-chunk ``numpy.linalg.svd`` calls
inside Spark executors (``BASELINE`` config 5, the Thunder usage pattern);
the straight translation — ``jnp.linalg.svd`` / ``jnp.linalg.eigvalsh`` on a
batch of small matrices — lowers to XLA's QR-iteration / QDWH loops, which
are built for one big matrix and leave a large batch of tiny problems
almost entirely serial.  This module takes the TPU-native route instead:

* :func:`jacobi_eigh` — batched symmetric eigendecomposition by cyclic
  Jacobi with the parallel (round-robin) ordering.  Every step applies
  n/2 disjoint rotations to the whole batch at once as two permutation
  gathers plus elementwise math — no matmuls, no data-dependent control
  flow, one fixed-length ``lax.scan``.  On a (1024, 16, 16) batch on a
  v5e chip: 29 ms for ``jnp.linalg.eigvalsh`` vs 7.7 ms standalone
  (~4x; ~2 ms marginal once fused into the Gram pipeline — the rest is
  this environment's per-dispatch floor), exact to f32 machine
  precision.
* :func:`svdvals` / :func:`tallskinny_pca` — singular values / principal
  components of tall-skinny blocks via the Gram matrix: the (n, d) data
  is touched once by an MXU matmul and the eigenproblem is only (d, d),
  routed to :func:`jacobi_eigh` when the batch is large enough to
  amortise the sweep chain (see ``_use_jacobi``), else XLA's QDWH.

Rotation angles use ``0.5 * atan2(2*a_pq, a_qq - a_pp)`` — no divisions,
no overflow for any input scale (the textbook ``tau = (a_qq - a_pp) /
(2*a_pq)`` route overflows f32 near convergence and, on TPU, turns into
NaN through the rsqrt lowering).  The row/column updates are pure
elementwise f32, so results do not depend on the MXU's bf16 default the
way a rotation-by-matmul formulation would.
"""

import math
from functools import lru_cache

import numpy as np

import jax
import jax.numpy as jnp

from bolt_tpu._precision import resolve as _resolve
from bolt_tpu.utils import prod


def _adjoint(x):
    """Conjugate transpose of the trailing two dims (plain transpose for
    real dtypes)."""
    xt = jnp.swapaxes(x, -1, -2)
    return jnp.conj(xt) if jnp.iscomplexobj(x) else xt


def _acc_dtype(dtype):
    """Accumulation dtype for the Gram matmul: widen half precisions to
    float32, never narrow (jax rejects a narrower preferred_element_type)."""
    if dtype in (jnp.bfloat16, jnp.float16):
        return jnp.float32
    return dtype


def _real_dtype(dtype):
    return jnp.finfo(dtype).dtype if jnp.issubdtype(dtype, jnp.complexfloating) \
        else dtype


@lru_cache(maxsize=None)
def _round_robin(n):
    """Parallel-ordering Jacobi schedule (the circle method): ``n`` even →
    ``n - 1`` rounds of ``n // 2`` disjoint (p, q) pairs covering every
    index, so one round rotates the whole matrix."""
    others = list(range(1, n))
    rounds = []
    for _ in range(n - 1):
        cur = [0] + others
        pairs = sorted((min(cur[i], cur[n - 1 - i]), max(cur[i], cur[n - 1 - i]))
                       for i in range(n // 2))
        rounds.append(pairs)
        others = others[-1:] + others[:-1]
    return np.asarray(rounds)  # (n-1, n//2, 2)


def _default_sweeps(n, dtype):
    """Cyclic Jacobi converges quadratically once sweeps ~ log2(n); the +4
    (+6 for f64's longer mantissa) lands at machine precision with margin —
    measured ≤ 2e-6 rel. error (f32) for n up to 64 on random Gram
    matrices."""
    extra = 6 if jnp.finfo(dtype).bits >= 64 else 4
    return max(6, int(math.ceil(math.log2(max(n, 2)))) + extra)


def jacobi_eigh(a, vectors=False, sweeps=None):
    """Batched symmetric/Hermitian-real eigendecomposition, TPU-first.

    Parameters mirror ``jnp.linalg.eigvalsh`` / ``eigh``: ``a`` is
    ``(..., n, n)`` symmetric real; returns ascending eigenvalues
    ``(..., n)``, or ``(w, v)`` with orthonormal columns ``a @ v = v * w``
    when ``vectors=True``.

    A fixed-iteration cyclic Jacobi with parallel ordering: ``sweeps *
    (n - 1)`` scan steps, each applying ``n // 2`` disjoint rotations to
    every matrix in the batch via two permutation gathers + elementwise
    arithmetic.  Best for large batches of small ``n`` (the per-chunk
    PCA regime); for a single big matrix prefer ``jnp.linalg.eigh``.
    Complex input falls back to ``jnp.linalg``.
    """
    a = jnp.asarray(a)
    if a.ndim < 2 or a.shape[-1] != a.shape[-2]:
        raise ValueError("jacobi_eigh requires (..., n, n), got %s"
                         % (a.shape,))
    if jnp.iscomplexobj(a):
        return (jnp.linalg.eigh(a) if vectors else jnp.linalg.eigvalsh(a))
    if not jnp.issubdtype(a.dtype, jnp.floating):
        a = a.astype(jnp.float64 if jax.config.jax_enable_x64 else jnp.float32)
    n = a.shape[-1]
    if sweeps is None:
        sweeps = _default_sweeps(n, a.dtype)
    odd = n % 2
    m = n + odd
    if odd:
        pad = [(0, 0)] * (a.ndim - 2) + [(0, 1), (0, 1)]
        a = jnp.pad(a, pad)
        # dummy diagonal above the spectral radius (Gershgorin: rho <=
        # m * max|a|, computed without squaring so f32 inputs near the
        # dtype max don't overflow): every (i, dummy) pair then rotates by
        # theta = 0.5*atan2(0, big - a_ii) = 0 and the dummy stays
        # decoupled (a zero diagonal would swap itself in via theta = pi/2
        # and scramble the spectrum)
        big = 1.0 + m * jnp.max(jnp.abs(a), axis=(-2, -1))
        a = a.at[..., n, n].set(big)

    sched = np.tile(_round_robin(m), (sweeps, 1, 1))      # (S, m//2, 2)
    P = sched[..., 0]
    Q = sched[..., 1]
    # per-round involution pi (p <-> q), precomputed host-side
    PI = np.tile(np.arange(m), (sched.shape[0], 1))
    rows = np.arange(sched.shape[0])[:, None]
    PI[rows, P] = Q
    PI[rows, Q] = P
    xs = (jnp.asarray(P), jnp.asarray(Q), jnp.asarray(PI))

    def rotate(M, pi, cv, sv, axis):
        # apply all n//2 disjoint rotations along one side:
        #   rows (axis=-2):  (Jt M)[i, :] = cv[i]*M[i, :] + sv[i]*M[pi[i], :]
        #   cols (axis=-1):  (M J)[:, j] = cv[j]*M[:, j] + sv[j]*M[:, pi[j]]
        coef = (cv[..., :, None], sv[..., :, None]) if axis == -2 \
            else (cv[..., None, :], sv[..., None, :])
        return coef[0] * M + coef[1] * jnp.take(M, pi, axis=axis)

    def step(carry, pqi):
        A, V = carry
        p, q, pi = pqi
        app = A[..., p, p]
        aqq = A[..., q, q]
        apq = A[..., p, q]
        theta = 0.5 * jnp.arctan2(2.0 * apq, aqq - app)
        c = jnp.cos(theta)
        s = jnp.sin(theta)
        zero = jnp.zeros(A.shape[:-2] + (m,), A.dtype)
        cv = zero.at[..., p].set(c).at[..., q].set(c)
        # both sides carry -s at p / +s at q:
        #   (Jt A)[p,:] = c A[p,:] - s A[q,:];  (B J)[:,p] = c B[:,p] - s B[:,q]
        sv = zero.at[..., p].set(-s).at[..., q].set(s)
        A = rotate(rotate(A, pi, cv, sv, -2), pi, cv, sv, -1)
        if V is not None:
            V = rotate(V, pi, cv, sv, -1)
        return (A, V), None

    V0 = jnp.broadcast_to(jnp.eye(m, dtype=a.dtype),
                          a.shape) if vectors else None
    (A, V), _ = jax.lax.scan(step, (a, V0), xs)
    w = jnp.diagonal(A, axis1=-2, axis2=-1)
    if odd:
        w = w[..., :n]   # dummy never swaps, so it is still at index n
    order = jnp.argsort(w, axis=-1)
    if not vectors:
        return jnp.take_along_axis(w, order, axis=-1)
    if odd:
        V = V[..., :n, :n]
    V = jnp.take_along_axis(V, order[..., None, :], axis=-1)
    return jnp.take_along_axis(w, order, axis=-1), V


# Jacobi-vs-QDWH routing, measured on a v5e chip (batched Gram matrices,
# steady state): jacobi wins 2.5-4.5x for d <= 64 at batch >= 1024 and
# still ~1.2-1.4x at batch 64 for d in [32, 64], but LOSES for small
# batch*d (the sequential sweep chain is launch-bound: d=16/batch=64 ->
# 0.6x) and for d = 128 (0.3x — per-step O(B d^2) gathers outgrow QDWH's
# matmuls).  Hence: small dims AND enough total work.
_JACOBI_MAX_DIM = 64
_JACOBI_MIN_WORK = 2048          # batch * d below this -> QDWH


def _is_batch_tracer(g):
    # jax 0.9 deprecates jax.interpreters.batching.BatchTracer (attribute
    # access raises), so isinstance-check via _src with a name-scan
    # fallback; if both ever fail the routing degrades to the (correct,
    # slower-for-vmapped-grams) QDWH path, never to a wrong result
    try:
        from jax._src.interpreters import batching
        if isinstance(g, batching.BatchTracer):
            return True
    except Exception:
        pass
    return any(c.__name__ == "BatchTracer" for c in type(g).__mro__)


def _true_batch(g):
    """Total batch count including vmapped dims: under vmap the outer
    batch is invisible in ``g.shape`` (the per-chunk svdvals usage —
    BASELINE config 5b — maps over the chunk grid, so a single (d, d)
    Gram at trace time is really a whole batch of them); walk the
    batching tracers to recover the true amortisation."""
    batch = prod(g.shape[:-2])
    t = g
    while _is_batch_tracer(t) and hasattr(t, "val"):
        inner = t.val
        batch *= max(prod(inner.shape) // max(prod(t.shape), 1), 1)
        t = inner
    return batch


def _use_jacobi(g):
    d = g.shape[-1]
    if d > _JACOBI_MAX_DIM or jnp.iscomplexobj(g):
        return False
    return _true_batch(g) * d >= _JACOBI_MIN_WORK


def _gram_eigvalsh(g):
    return jacobi_eigh(g) if _use_jacobi(g) else jnp.linalg.eigvalsh(g)


def svdvals(x, gram_ratio=4):
    """Singular values of a (possibly batched) matrix, TPU-first.

    For tall-skinny blocks (rows >= ``gram_ratio`` * cols) — the shape of
    the reference's PCA workload (``BASELINE`` config 5: per-chunk SVD on
    ``(N, features)``) — the values come from the Gram matrix:
    ``sqrt(eigvalsh(x.T @ x))``.  The matmul runs on the MXU, and the
    eigendecomposition touches only a (cols, cols) matrix — routed to the
    batched :func:`jacobi_eigh` when cols <= 64 and the batch (or a
    vmapped context) amortises it, else XLA's QDWH — instead of XLA's
    QR-iteration SVD over the full block.  The trade-off is the classic
    one: forming the Gram matrix squares the condition number, so trailing
    singular values below ``sqrt(eps) * s_max`` lose accuracy — fine for
    PCA-style spectra, not for rank-revealing use.  Wide or near-square
    inputs fall back to ``jnp.linalg.svd``.
    """
    x = _widen(jnp.asarray(x), jnp)
    rows, cols = x.shape[-2], x.shape[-1]
    if rows >= gram_ratio * cols:
        g = jnp.matmul(_adjoint(x), x, precision=_resolve("highest"),
                       preferred_element_type=_acc_dtype(x.dtype))
        ev = _gram_eigvalsh(g)                         # ascending, real
        ev = jnp.maximum(ev[..., ::-1], 0.0)           # descending, clamped
        return jnp.sqrt(ev).astype(_real_dtype(x.dtype))
    return jnp.linalg.svd(x, compute_uv=False)


def _check_k(k, d):
    """Validate a component-count request against ``d`` features; None
    means all."""
    if k is None:
        return d
    if not 1 <= k <= d:
        raise ValueError("k=%d out of range for %d features" % (k, d))
    return k


def _gram(x, xp, precision="highest"):
    """The Gram matrix ``X^H X`` of ``(..., n, d)`` data — one MXU matmul
    on TPU ("highest" precision, f32 accumulation, unless the caller
    resolved a cheaper mode through the scoped policy)."""
    xt = xp.swapaxes(x, -1, -2)
    if xp.iscomplexobj(x):
        xt = xp.conj(xt)
    return xp.matmul(xt, x) if xp is np else \
        xp.matmul(xt, x, precision=precision,
                  preferred_element_type=_acc_dtype(x.dtype))


def _decompose_gram(g, k, xp, eigh_fn):
    """Eigendecompose a Gram matrix: returns ``(vec (d, k), ev (k,))`` in
    descending order with negative eigenvalues clamped to zero."""
    ev, vec = eigh_fn(g)                               # ascending
    ev = xp.maximum(ev[..., ::-1], 0.0)[..., :k]       # descending, clamped
    vec = vec[..., ::-1][..., :k]
    return vec, ev


def _gram_decompose(x, k, xp, eigh_fn):
    """Shared Gram-route core for the PCA family: ``x`` is ``(n, d)``,
    returns ``(vec (d, k), ev (k,))`` in descending order.  ``xp`` is the
    array namespace (numpy for the local oracle, jnp inside jit) so the
    backends run the same sequence (the TPU pca program splices its
    centering fold between :func:`_gram` and :func:`_decompose_gram`)."""
    return _decompose_gram(_gram(x, xp), k, xp, eigh_fn)


def _tpu_eigh(g):
    if _use_jacobi(g):
        return jacobi_eigh(g, vectors=True)
    return jnp.linalg.eigh(g)


def _widen(x, xp):
    """Promote to a float dtype the decomposition can run in (ints would
    silently truncate components to zero)."""
    if not xp.issubdtype(x.dtype, xp.inexact):
        return x.astype(xp.float64 if (xp is np or jax.config.jax_enable_x64)
                        else xp.float32)
    if x.dtype in (jnp.bfloat16, jnp.float16):
        return x.astype(jnp.float32)
    return x


def lstsq(a, b):
    """Least-squares solution of tall-skinny ``a @ x ~ b``, TPU-first.

    ``a`` is ``(..., n, d)`` with ``n >= d`` and full column rank; ``b``
    is ``(..., n)`` or ``(..., n, k)``.  Returns ``x`` shaped
    ``(..., d)`` / ``(..., d, k)``.  Solved through :func:`tsqr`
    (CholeskyQR2): the O(n d^2) work is explicit-precision MXU matmuls,
    the triangular solve touches only (d, d), and one residual-refinement
    step scrubs the solve's rounding — no column-serial Householder
    sweep.  Same conditioning envelope as :func:`tsqr` (cond(a) up to
    ~1/sqrt(eps)); for rank-deficient or ill-conditioned systems use
    ``jnp.linalg.lstsq``.

    ``a`` (and ``b``) may also be bolt arrays: records are the rows (key
    axes flatten to ``n`` — axis 0 on the local backend), value axes
    flatten to the ``d`` features / ``k`` targets.  On mode 'tpu' the
    data stays sharded and GSPMD inserts the all-reduce for the
    Gram-sized contractions (unlike :func:`pca` this is not one cached
    program — a deferred chain materialises first).  Memory: the thin
    ``q`` is materialised at the size of ``a`` — for HBM-filling systems
    form the normal equations from Gram blocks instead (the
    :func:`tallskinny_pca` machinery).
    """
    if getattr(a, "mode", None) == "tpu":
        n = prod(a.shape[:a.split])
        a = a.tojax().reshape((n, prod(a.shape[a.split:])))
    elif getattr(a, "mode", None) == "local":
        a = np.asarray(a).reshape((a.shape[0], -1))
    if getattr(b, "mode", None) == "tpu":
        n = prod(b.shape[:b.split])
        rest = prod(b.shape[b.split:])
        bj = b.tojax()
        b = bj.reshape((n,)) if b.ndim == b.split else bj.reshape((n, rest))
    elif getattr(b, "mode", None) == "local":
        bl = np.asarray(b)
        b = bl if bl.ndim == 1 else bl.reshape((bl.shape[0], -1))
    a = _widen(jnp.asarray(a), jnp)
    b = _widen(jnp.asarray(b), jnp)
    if jnp.iscomplexobj(a) or jnp.iscomplexobj(b):
        raise ValueError("lstsq supports real systems; use jnp.linalg.lstsq "
                         "for complex ones")
    # promote, never narrow (an f64 b must not silently drop to f32 a)
    dt = jnp.promote_types(a.dtype, b.dtype)
    a, b = a.astype(dt), b.astype(dt)
    vec = b.ndim == a.ndim - 1
    if a.ndim < 2 or (not vec and b.ndim != a.ndim) \
            or b.shape[-2 if not vec else -1] != a.shape[-2]:
        raise ValueError(
            "lstsq needs a (..., n, d) and b (..., n) or (..., n, k); got "
            "%s and %s" % (a.shape, b.shape))
    if vec:
        b = b[..., None]
    q, r = tsqr(a)
    y = jnp.matmul(_adjoint(q), b, precision=_resolve("highest"))
    x = jax.scipy.linalg.solve_triangular(r, y, lower=False)
    # one refinement pass: e = y - r x at full precision repairs the
    # solve's blocked-matmul rounding (see tsqr's r_inv note)
    e = y - jnp.matmul(r, x, precision=_resolve("highest"))
    x = x + jax.scipy.linalg.solve_triangular(r, e, lower=False)
    return x[..., 0] if vec else x


def tallskinny_svd(x, k=None):
    """Thin SVD ``(u, s, vh)`` of tall-skinny (batched) matrices via the
    Gram route: one MXU matmul over the ``(..., n, d)`` data, a (d, d)
    eigenproblem (:func:`jacobi_eigh` when ``d <= 64`` and the batch
    amortises it — see ``_use_jacobi``), and one more matmul for
    ``u = x @ v / s``.  Same accuracy trade-off as
    :func:`svdvals` (condition number squares): singular triplets below
    ``sqrt(eps) * s_max`` lose accuracy, and for exactly zero singular
    values the corresponding ``u`` columns are returned as zeros rather
    than an arbitrary orthonormal completion.  ``k`` truncates to the
    top components.  Descending order, ``numpy.linalg.svd`` conventions.
    """
    x = _widen(jnp.asarray(x), jnp)
    if x.ndim < 2 or x.shape[-2] < x.shape[-1]:
        raise ValueError("tallskinny_svd requires (..., n, d) with n >= d, "
                         "got %s; use jnp.linalg.svd" % (x.shape,))
    d = x.shape[-1]
    vec, ev = _gram_decompose(x, _check_k(k, d), jnp, _tpu_eigh)
    s = jnp.sqrt(ev)
    safe = jnp.where(s > 0, s, 1.0)
    u = jnp.matmul(x, vec, precision=_resolve("highest")) / safe[..., None, :]
    u = jnp.where(s[..., None, :] > 0, u, 0.0)
    return u, s.astype(_real_dtype(x.dtype)), _adjoint(vec)


def tsqr(x):
    """Thin QR of tall-skinny (batched) matrices by CholeskyQR2, TPU-first.

    ``x`` is ``(..., n, d)`` with ``n >= d``; returns ``(q, r)`` with
    orthonormal ``q`` (same shape), upper-triangular ``r`` with positive
    diagonal, and ``q @ r == x``.  Two rounds of ``R = chol(X^T X)^T;
    Q = X R^{-1}`` — all MXU matmuls and a (d, d) Cholesky, no
    column-by-column Householder loop (XLA's ``qr`` is serial in d and
    built for one big matrix).  CholeskyQR2's orthogonality error is
    ~machine-eps for cond(x) up to ~1/sqrt(eps) — beyond that (or rank
    deficient, where the Cholesky NaNs) use ``jnp.linalg.qr``.
    """
    x = _widen(jnp.asarray(x), jnp)
    if x.ndim < 2 or x.shape[-2] < x.shape[-1]:
        raise ValueError("tsqr requires (..., n, d) with n >= d, got %s"
                         % (x.shape,))

    d = x.shape[-1]
    eye = jnp.eye(d, dtype=x.dtype)

    def _chol_qr(a):
        g = jnp.matmul(_adjoint(a), a, precision=_resolve("highest"),
                       preferred_element_type=_acc_dtype(a.dtype))
        l = jnp.linalg.cholesky(g)                       # g = l @ l^H
        r = _adjoint(l)
        # invert only the small (d, d) triangle, then apply by matmul so
        # the O(n d^2) work runs at controlled precision (TPU's
        # TriangularSolve applies blocked matmuls at the bf16 default,
        # which would cap orthogonality ~1e-3 on f32 data).  One Newton
        # step X <- X(2I - RX) at precision="highest" scrubs the solve's
        # own rounding back to f32 eps.
        r_inv = _adjoint(jax.scipy.linalg.solve_triangular(
            l, jnp.broadcast_to(eye, l.shape), lower=True))
        correction = 2.0 * eye - jnp.matmul(r, r_inv, precision=_resolve("highest"))
        r_inv = jnp.matmul(r_inv, correction, precision=_resolve("highest"))
        q = jnp.matmul(a, r_inv, precision=_resolve("highest"))
        return q, r

    q1, r1 = _chol_qr(x)
    q, r2 = _chol_qr(q1)                                 # re-orthogonalise
    return q, jnp.matmul(r2, r1, precision=_resolve("highest"))


def pca(b, k=None, center=False, axis=None, return_mean=False,
        fetch=True, precision=None):
    """Distributed PCA of a bolt array: sample axes x feature axes, all
    in ONE compiled SPMD program.

    The reference ecosystem runs this workload by chunking the sample
    axis and doing per-chunk ``numpy.linalg.svd`` inside Spark executors
    (BASELINE config 5 is its kernel).  Here the whole decomposition is
    a single XLA program over the sharded array: the Gram matrix
    ``X^T X`` is one MXU matmul per shard whose partial products GSPMD
    combines with an ICI all-reduce (the ``rdd.aggregate`` tree of
    SURVEY §3.4, lowered to hardware), the small (d, d) eigenproblem is
    solved on-device (a single matrix routes to XLA's QDWH eigh; large
    batches take :func:`jacobi_eigh`), and the projection
    ``X @ V`` runs shard-local.  Scores keep the input's key sharding;
    data never gathers to one device or host.

    Parameters: ``b`` — a bolt array (TPU or local mode; locals run the
    same Gram route in NumPy, except that with ``center=True`` the TPU
    program folds the centering into the Gram algebraically
    (``Gc = G - n mu mu^H`` — the centred matrix is never materialised)
    while the oracle subtracts the mean explicitly: results agree to
    ~``eps_f32 * (||mu||/sigma)^2`` relative — exact for mean-zero data,
    ~1e-2 at a 200-sigma offset; pre-shift data with larger offsets);
    ``k`` — number of components (default: all
    ``d``); ``center`` — subtract per-feature means first (adds one
    fused pass + a tiny psum); ``axis`` — the sample axes, like
    ``map``'s (default: the TPU array's key axes / axis 0 locally;
    a TPU array aligns by swapping when they differ, reference
    ``_align`` semantics).

    Returns ``(scores, components, singular_values)``: scores is a bolt
    array shaped ``sample_shape + (k,)`` with the input's mode (and key
    sharding on TPU); components ``(d, k)`` and singular values ``(k,)``
    are NumPy arrays (descending).  With ``return_mean=True`` a fourth
    element is the per-feature mean ``(d,)`` that was subtracted (zeros
    when ``center=False``) — needed to project NEW data consistently:
    ``scores_new = (x_new - mean) @ components``.

    ``fetch=False`` (TPU mode) returns components/singular values/mean
    as DEVICE-resident ``jax.Array``s instead of host ndarrays: the call
    then syncs nothing — back-to-back pca calls (or downstream jnp use
    of the components) pipeline without paying a host round-trip each,
    which on a remote attach is the dominant per-call cost.

    ``precision=None`` resolves through the scoped policy
    (``bolt.precision``), pinned at ``"highest"`` — the Gram and
    projection matmuls are the measured ~2x of this op's cost;
    ``"default"`` trades ~1e-2 relative score accuracy for it
    (BASELINE round-4 MFU table).  The local oracle always computes in
    f64.
    """
    from bolt_tpu._precision import resolve
    pr = resolve(precision)
    mode, b, x_full, split, shape, n, d = _samples_features(
        b, axis, "pca", hint="; for plain matrices use tallskinny_pca")
    kshape = shape[:split]
    if n < d:
        raise ValueError(
            "pca requires #samples >= #features (got %d x %d); swap your "
            "key/value axes or use jnp.linalg.svd" % (n, d))
    k = _check_k(k, d)

    if mode == "local":
        # the NumPy oracle: same sequence, host-side
        x = _widen(x_full.reshape(n, d), np)
        mu = x.mean(axis=0) if center else np.zeros(d, x.dtype)
        if center:
            x = x - mu
        vec, ev = _gram_decompose(x, k, np, np.linalg.eigh)
        vec = np.ascontiguousarray(vec)
        scores = (x @ vec).reshape(kshape + (k,))
        out = (type(b)(scores), vec,
               np.sqrt(ev).astype(_real_dtype(x.dtype)))
        return out + (mu,) if return_mean else out

    from bolt_tpu.parallel.sharding import key_sharding
    from bolt_tpu.tpu.array import _cached_jit, _chain_apply
    # a deferred map chain fuses INTO the PCA program (one XLA program,
    # no materialised intermediate), same as map/filter/reduce consumers
    base, funcs = b._chain_parts()
    mesh = b._mesh

    def build():
        def program(data):
            mapped = _chain_apply(funcs, split, data)
            x = _widen(mapped.reshape((n, d)), jnp)
            # Centering folds into the Gram algebraically (round-4 fusion):
            #   (X - mu)^T (X - mu) = X^T X - n mu mu^T
            # so the centred matrix is NEVER materialised — the raw X is
            # read by exactly two MXU matmuls (Gram + projection) plus the
            # mean's fused reduction, instead of a mean pass, a centred
            # copy (read+write), and two matmuls over the copy.  The
            # projection offset is applied to the (k,)-sized result:
            #   (X - mu) @ V = X @ V - mu @ V.
            # Conditioning: the fold loses the centred formulation's
            # guard against cancellation when ||mu|| >> sigma — the Gram
            # loses ~eps_f32 * (mu/sigma)^2 relative accuracy (measured:
            # ~1e-4 at 20 sigma, ~1e-2 at 200 sigma — see
            # test_pca_centering_fold_large_offset).  Pre-shift data with
            # larger offsets.
            mu = jnp.mean(x, axis=0) if center else jnp.zeros(d, x.dtype)
            g = _gram(x, jnp, pr)
            if center:
                g = g - n * jnp.outer(jnp.conj(mu), mu)
            vec, ev = _decompose_gram(g, k, jnp, _tpu_eigh)
            # pinned "highest": the MXU's bf16 default costs ~3 decimal
            # digits on f32 data — visible in scores at PCA scale; the
            # scoped policy buys it back where the user accepts that
            scores = jnp.matmul(x, vec, precision=pr)
            if center:
                scores = scores - jnp.matmul(mu, vec, precision=pr)
            scores = scores.reshape(kshape + (k,))
            scores = jax.lax.with_sharding_constraint(
                scores, key_sharding(mesh, kshape + (k,), split))
            return scores, vec, jnp.sqrt(ev), mu
        return jax.jit(program)

    fn = _cached_jit(("ops-pca", funcs, base.shape, str(base.dtype), split,
                      mesh, k, center, pr), build)
    scores, vec, sv, mu = fn(base)
    wrapped = type(b)(scores, split, mesh)
    if not fetch:
        # async path: nothing syncs — small results stay on device
        return (wrapped, vec, sv, mu) if return_mean else (wrapped, vec, sv)
    # ONE batched host fetch for the small results: separate device_gets
    # cost a full host round-trip EACH (2x the per-call latency of the
    # whole API on a remote attach; measured in the pca perf family)
    if return_mean:
        vec, sv, mu = jax.device_get((vec, sv, mu))
        return wrapped, np.asarray(vec), np.asarray(sv), np.asarray(mu)
    vec, sv = jax.device_get((vec, sv))
    return wrapped, np.asarray(vec), np.asarray(sv)


def tallskinny_pca(x, k=None):
    """Principal components of a tall-skinny ``(n, d)`` matrix via the
    Gram route: eigendecompose ``x.T @ x`` (d x d, MXU matmul; Jacobi
    when ``_use_jacobi`` says the shape profits), return
    ``(components (d, k), singular_values
    (k,))`` in descending order.  The reference runs this workload as
    per-chunk SVD through Spark (``BASELINE`` config 5); here the big
    matmul is the only pass over the data."""
    n, d = x.shape
    if n < d:
        raise ValueError(
            "tallskinny_pca requires n >= d (got %d x %d): the rank-%d Gram "
            "matrix would pad the spectrum with zero eigenvalues whose "
            "eigenvectors are arbitrary; use jnp.linalg.svd" % (n, d, n))
    x = _widen(jnp.asarray(x), jnp)
    vec, ev = _gram_decompose(x, _check_k(k, d), jnp, _tpu_eigh)
    return vec.astype(x.dtype), jnp.sqrt(ev).astype(_real_dtype(x.dtype))


def _samples_features(b, axis, name, hint=""):
    """Shared samples×features preamble for :func:`pca`/:func:`cov`:
    mode dispatch, sample-axis resolution (``_align`` on TPU, moveaxis
    locally), and the flattened (n, d) sizes.  Returns
    ``(mode, b, x_full, split, shape, n, d)`` where ``x_full`` is the
    axis-aligned host array in local mode (None on TPU)."""
    from bolt_tpu.utils import tupleize

    mode = getattr(b, "mode", None)
    if mode not in ("local", "tpu"):
        raise TypeError("%s expects a bolt array (mode 'local' or 'tpu')%s"
                        % (name, hint))
    if mode == "tpu":
        axes = sorted(tupleize(axis)) if axis is not None \
            else list(range(b.split))
        b = b._align(axes)
        split = b.split
        x_full = None
        shape = b.shape
    else:
        axes = sorted(tupleize(axis)) if axis is not None else [0]
        split = len(axes)
        # move sample axes to the front (the local analog of _align)
        x_full = np.moveaxis(np.asarray(b), axes, range(split))
        shape = x_full.shape
    return mode, b, x_full, split, shape, prod(shape[:split]), prod(shape[split:])


def cov(b, axis=None, center=True, ddof=1, return_mean=False,
        precision=None):
    """Feature-covariance matrix of a bolt array viewed as samples ×
    features, in ONE compiled SPMD program.

    Same sample/feature split as :func:`pca` (``axis`` names the sample
    axes, defaulting to the key axes / axis 0 locally; features are the
    flattened remaining axes): the centred Gram matmul runs shard-local
    on the MXU and GSPMD all-reduces the (d, d) partial products — data
    never gathers.  ``ddof=1`` gives the sample covariance (numpy's
    ``np.cov`` default); ``center=False`` divides the raw second moment
    ``X^T X`` by ``n - ddof`` instead.  Like :func:`pca`, the TPU
    program folds the centering into the Gram algebraically (the local
    oracle subtracts the mean explicitly) — entries lose
    ~``eps_f32 * (||mu||/sigma)^2`` relative accuracy at large mean
    offsets.  Returns a (d, d) NumPy array;
    ``return_mean=True`` appends the per-feature mean.  Superset of the
    reference (its ecosystem computes this via per-chunk jobs).
    ``precision=None`` resolves through the scoped policy like
    :func:`pca` (the Gram matmul is the cost)."""
    from bolt_tpu._precision import resolve
    pr = resolve(precision)
    mode, b, x_full, split, shape, n, d = _samples_features(b, axis, "cov")
    if n - ddof <= 0:
        raise ValueError("cov needs more than ddof=%d samples, got %d"
                         % (ddof, n))

    if mode == "local":
        x = _widen(x_full.reshape(n, d), np)
        mu = x.mean(axis=0) if center else np.zeros(d, x.dtype)
        if center:
            x = x - mu
        # np.cov convention: C_ij = E[(x_i - mu_i) conj(x_j - mu_j)] —
        # the conjugate is on the SECOND factor
        c = (x.T @ np.conj(x)) / (n - ddof)
        return (c, mu) if return_mean else c

    from bolt_tpu.tpu.array import _cached_jit, _chain_apply
    base, funcs = b._chain_parts()
    mesh = b._mesh

    def build():
        def program(data):
            mapped = _chain_apply(funcs, split, data)
            x = _widen(mapped.reshape((n, d)), jnp)
            # same centering fold as pca (round 4): the centred copy is
            # never materialised — (X-mu)^T conj(X-mu) = X^T conj(X) -
            # n mu conj(mu)^T; same second-factor conjugation as np.cov.
            # Same conditioning envelope as pca's fold (~eps_f32 *
            # (mu/sigma)^2 relative error in the entries).
            mu = jnp.mean(x, axis=0) if center else jnp.zeros(d, x.dtype)
            c = jnp.matmul(jnp.swapaxes(x, -1, -2), jnp.conj(x),
                           precision=pr,
                           preferred_element_type=_acc_dtype(x.dtype))
            if center:
                c = c - n * jnp.outer(mu, jnp.conj(mu))
                # the explicit-centering path this fold replaced computed
                # Xc^H Xc, whose diagonal (sum of squared moduli) cannot
                # go negative; the fold can cancel past f32 precision for
                # tiny-variance features on a large offset, so restore
                # the invariant (mirrors _decompose_gram's eigenvalue
                # clamp) — corrcoef's sqrt(diag) depends on it
                idx = jnp.arange(d)
                diag = jnp.maximum(jnp.real(c[idx, idx]), 0.0)
                c = c.at[idx, idx].set(diag.astype(c.dtype))
            return c / (n - ddof), mu
        return jax.jit(program)

    fn = _cached_jit(("ops-cov", funcs, base.shape, str(base.dtype), split,
                      mesh, center, ddof, pr), build)
    c, mu = fn(base)
    if return_mean:
        c, mu = jax.device_get((c, mu))    # one batched round-trip
        return np.asarray(c), np.asarray(mu)
    return np.asarray(jax.device_get(c))


def corrcoef(b, axis=None, precision=None):
    """Feature-correlation matrix (Pearson) of a bolt array viewed as
    samples × features: :func:`cov` normalised by the outer product of
    the per-feature standard deviations (the (d, d) result is tiny, so
    the normalisation runs on host).  Zero-variance features yield
    NaN rows/columns, matching ``np.corrcoef``.  ``precision`` threads
    to the cov Gram like :func:`pca`'s."""
    c = cov(b, axis=axis, center=True, ddof=1, precision=precision)
    sd = np.sqrt(np.diag(c))
    with np.errstate(divide="ignore", invalid="ignore"):
        r = c / np.outer(sd, sd)
    return r
