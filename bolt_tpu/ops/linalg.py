"""TPU-first dense linear algebra for the per-chunk workloads.

The reference runs its PCA workload as per-chunk ``numpy.linalg.svd`` calls
inside Spark executors (``BASELINE`` config 5, the Thunder usage pattern);
the straight translation — ``jnp.linalg.svd`` / ``jnp.linalg.eigvalsh`` on a
batch of small matrices — lowers to XLA's QR-iteration / QDWH loops, which
are built for one big matrix and leave a large batch of tiny problems
almost entirely serial.  This module takes the TPU-native route instead:

* :func:`jacobi_eigh` — batched symmetric eigendecomposition by cyclic
  Jacobi with the parallel (round-robin) ordering.  Every step applies
  n/2 disjoint rotations to the whole batch at once as two permutation
  gathers plus elementwise math — no matmuls, no data-dependent control
  flow, one fixed-length ``lax.scan``.  On a (1024, 16, 16) batch on a
  v5e chip: 29 ms for ``jnp.linalg.eigvalsh`` vs 7.7 ms standalone
  (~4x; ~2 ms marginal once fused into the Gram pipeline — the rest is
  this environment's per-dispatch floor), exact to f32 machine
  precision.
* :func:`svdvals` / :func:`tallskinny_pca` — singular values / principal
  components of tall-skinny blocks via the Gram matrix: the (n, d) data
  is touched once by an MXU matmul and the eigenproblem is only (d, d),
  solved by :func:`jacobi_eigh` when d is small.

Rotation angles use ``0.5 * atan2(2*a_pq, a_qq - a_pp)`` — no divisions,
no overflow for any input scale (the textbook ``tau = (a_qq - a_pp) /
(2*a_pq)`` route overflows f32 near convergence and, on TPU, turns into
NaN through the rsqrt lowering).  The row/column updates are pure
elementwise f32, so results do not depend on the MXU's bf16 default the
way a rotation-by-matmul formulation would.
"""

import math
from functools import lru_cache

import numpy as np

import jax
import jax.numpy as jnp


def _adjoint(x):
    """Conjugate transpose of the trailing two dims (plain transpose for
    real dtypes)."""
    xt = jnp.swapaxes(x, -1, -2)
    return jnp.conj(xt) if jnp.iscomplexobj(x) else xt


def _acc_dtype(dtype):
    """Accumulation dtype for the Gram matmul: widen half precisions to
    float32, never narrow (jax rejects a narrower preferred_element_type)."""
    if dtype in (jnp.bfloat16, jnp.float16):
        return jnp.float32
    return dtype


def _real_dtype(dtype):
    return jnp.finfo(dtype).dtype if jnp.issubdtype(dtype, jnp.complexfloating) \
        else dtype


@lru_cache(maxsize=None)
def _round_robin(n):
    """Parallel-ordering Jacobi schedule (the circle method): ``n`` even →
    ``n - 1`` rounds of ``n // 2`` disjoint (p, q) pairs covering every
    index, so one round rotates the whole matrix."""
    others = list(range(1, n))
    rounds = []
    for _ in range(n - 1):
        cur = [0] + others
        pairs = sorted((min(cur[i], cur[n - 1 - i]), max(cur[i], cur[n - 1 - i]))
                       for i in range(n // 2))
        rounds.append(pairs)
        others = others[-1:] + others[:-1]
    return np.asarray(rounds)  # (n-1, n//2, 2)


def _default_sweeps(n, dtype):
    """Cyclic Jacobi converges quadratically once sweeps ~ log2(n); the +4
    (+6 for f64's longer mantissa) lands at machine precision with margin —
    measured ≤ 2e-6 rel. error (f32) for n up to 64 on random Gram
    matrices."""
    extra = 6 if jnp.finfo(dtype).bits >= 64 else 4
    return max(6, int(math.ceil(math.log2(max(n, 2)))) + extra)


def jacobi_eigh(a, vectors=False, sweeps=None):
    """Batched symmetric/Hermitian-real eigendecomposition, TPU-first.

    Parameters mirror ``jnp.linalg.eigvalsh`` / ``eigh``: ``a`` is
    ``(..., n, n)`` symmetric real; returns ascending eigenvalues
    ``(..., n)``, or ``(w, v)`` with orthonormal columns ``a @ v = v * w``
    when ``vectors=True``.

    A fixed-iteration cyclic Jacobi with parallel ordering: ``sweeps *
    (n - 1)`` scan steps, each applying ``n // 2`` disjoint rotations to
    every matrix in the batch via two permutation gathers + elementwise
    arithmetic.  Best for large batches of small ``n`` (the per-chunk
    PCA regime); for a single big matrix prefer ``jnp.linalg.eigh``.
    Complex input falls back to ``jnp.linalg``.
    """
    a = jnp.asarray(a)
    if a.ndim < 2 or a.shape[-1] != a.shape[-2]:
        raise ValueError("jacobi_eigh requires (..., n, n), got %s"
                         % (a.shape,))
    if jnp.iscomplexobj(a):
        return (jnp.linalg.eigh(a) if vectors else jnp.linalg.eigvalsh(a))
    if not jnp.issubdtype(a.dtype, jnp.floating):
        a = a.astype(jnp.float64 if jax.config.jax_enable_x64 else jnp.float32)
    n = a.shape[-1]
    if sweeps is None:
        sweeps = _default_sweeps(n, a.dtype)
    odd = n % 2
    m = n + odd
    if odd:
        pad = [(0, 0)] * (a.ndim - 2) + [(0, 1), (0, 1)]
        a = jnp.pad(a, pad)
        # dummy diagonal above the spectral radius (Gershgorin: rho <=
        # m * max|a|, computed without squaring so f32 inputs near the
        # dtype max don't overflow): every (i, dummy) pair then rotates by
        # theta = 0.5*atan2(0, big - a_ii) = 0 and the dummy stays
        # decoupled (a zero diagonal would swap itself in via theta = pi/2
        # and scramble the spectrum)
        big = 1.0 + m * jnp.max(jnp.abs(a), axis=(-2, -1))
        a = a.at[..., n, n].set(big)

    sched = np.tile(_round_robin(m), (sweeps, 1, 1))      # (S, m//2, 2)
    P = sched[..., 0]
    Q = sched[..., 1]
    # per-round involution pi (p <-> q), precomputed host-side
    PI = np.tile(np.arange(m), (sched.shape[0], 1))
    rows = np.arange(sched.shape[0])[:, None]
    PI[rows, P] = Q
    PI[rows, Q] = P
    xs = (jnp.asarray(P), jnp.asarray(Q), jnp.asarray(PI))

    def rotate(M, pi, cv, sv, axis):
        # apply all n//2 disjoint rotations along one side:
        #   rows (axis=-2):  (Jt M)[i, :] = cv[i]*M[i, :] + sv[i]*M[pi[i], :]
        #   cols (axis=-1):  (M J)[:, j] = cv[j]*M[:, j] + sv[j]*M[:, pi[j]]
        coef = (cv[..., :, None], sv[..., :, None]) if axis == -2 \
            else (cv[..., None, :], sv[..., None, :])
        return coef[0] * M + coef[1] * jnp.take(M, pi, axis=axis)

    def step(carry, pqi):
        A, V = carry
        p, q, pi = pqi
        app = A[..., p, p]
        aqq = A[..., q, q]
        apq = A[..., p, q]
        theta = 0.5 * jnp.arctan2(2.0 * apq, aqq - app)
        c = jnp.cos(theta)
        s = jnp.sin(theta)
        zero = jnp.zeros(A.shape[:-2] + (m,), A.dtype)
        cv = zero.at[..., p].set(c).at[..., q].set(c)
        # both sides carry -s at p / +s at q:
        #   (Jt A)[p,:] = c A[p,:] - s A[q,:];  (B J)[:,p] = c B[:,p] - s B[:,q]
        sv = zero.at[..., p].set(-s).at[..., q].set(s)
        A = rotate(rotate(A, pi, cv, sv, -2), pi, cv, sv, -1)
        if V is not None:
            V = rotate(V, pi, cv, sv, -1)
        return (A, V), None

    V0 = jnp.broadcast_to(jnp.eye(m, dtype=a.dtype),
                          a.shape) if vectors else None
    (A, V), _ = jax.lax.scan(step, (a, V0), xs)
    w = jnp.diagonal(A, axis1=-2, axis2=-1)
    if odd:
        w = w[..., :n]   # dummy never swaps, so it is still at index n
    order = jnp.argsort(w, axis=-1)
    if not vectors:
        return jnp.take_along_axis(w, order, axis=-1)
    if odd:
        V = V[..., :n, :n]
    V = jnp.take_along_axis(V, order[..., None, :], axis=-1)
    return jnp.take_along_axis(w, order, axis=-1), V


# past this, the Gram-route eigenproblem is better served by QDWH eigh
_JACOBI_MAX_DIM = 64


def _gram_eigvalsh(g):
    return jacobi_eigh(g) if g.shape[-1] <= _JACOBI_MAX_DIM \
        else jnp.linalg.eigvalsh(g)


def svdvals(x, gram_ratio=4):
    """Singular values of a (possibly batched) matrix, TPU-first.

    For tall-skinny blocks (rows >= ``gram_ratio`` * cols) — the shape of
    the reference's PCA workload (``BASELINE`` config 5: per-chunk SVD on
    ``(N, features)``) — the values come from the Gram matrix:
    ``sqrt(eigvalsh(x.T @ x))``.  The matmul runs on the MXU, and the
    eigendecomposition touches only a (cols, cols) matrix — solved by the
    batched :func:`jacobi_eigh` when cols <= 64 — instead of XLA's
    QR-iteration SVD over the full block.  The trade-off is the classic
    one: forming the Gram matrix squares the condition number, so trailing
    singular values below ``sqrt(eps) * s_max`` lose accuracy — fine for
    PCA-style spectra, not for rank-revealing use.  Wide or near-square
    inputs fall back to ``jnp.linalg.svd``.
    """
    rows, cols = x.shape[-2], x.shape[-1]
    if rows >= gram_ratio * cols:
        g = jnp.matmul(_adjoint(x), x,
                       preferred_element_type=_acc_dtype(x.dtype))
        ev = _gram_eigvalsh(g)                         # ascending, real
        ev = jnp.maximum(ev[..., ::-1], 0.0)           # descending, clamped
        return jnp.sqrt(ev).astype(_real_dtype(x.dtype))
    return jnp.linalg.svd(x, compute_uv=False)


def tallskinny_pca(x, k=None):
    """Principal components of a tall-skinny ``(n, d)`` matrix via the
    Gram route: eigendecompose ``x.T @ x`` (d x d, MXU matmul; batched
    Jacobi when d <= 64), return ``(components (d, k), singular_values
    (k,))`` in descending order.  The reference runs this workload as
    per-chunk SVD through Spark (``BASELINE`` config 5); here the big
    matmul is the only pass over the data."""
    n, d = x.shape
    if n < d:
        raise ValueError(
            "tallskinny_pca requires n >= d (got %d x %d): the rank-%d Gram "
            "matrix would pad the spectrum with zero eigenvalues whose "
            "eigenvectors are arbitrary; use jnp.linalg.svd" % (n, d, n))
    g = jnp.matmul(_adjoint(x), x, preferred_element_type=_acc_dtype(x.dtype))
    if d <= _JACOBI_MAX_DIM and not jnp.iscomplexobj(g):
        ev, vec = jacobi_eigh(g, vectors=True)         # ascending
    else:
        ev, vec = jnp.linalg.eigh(g)
    ev = jnp.maximum(ev[::-1], 0.0)
    vec = vec[:, ::-1]
    if k is not None:
        ev, vec = ev[:k], vec[:, :k]
    return vec.astype(x.dtype), jnp.sqrt(ev).astype(_real_dtype(x.dtype))
