"""Whole-array histogram as one compiled SPMD program.

The reference ecosystem computes histograms by mapping per-record
``np.histogram`` and combining counts on the driver; here the bucketise +
count runs sharded (GSPMD inserts the cross-device reduction for the
bincount) and the host receives only ``bins`` integers and ``bins + 1``
edges — nothing scales with the array.  Extension beyond the reference
(``bolt/spark/array.py`` has no histogram; symbol-level cite, SURVEY §0).
"""

import numpy as np

import jax
import jax.numpy as jnp


def histogram(b, bins=10, range=None, density=False):
    """``numpy.histogram`` semantics over ALL elements of a bolt array
    (flattened, like numpy): returns ``(counts, edges)`` as host ndarrays.

    ``bins`` is a static int (data-dependent bin counts cannot compile);
    ``range=None`` derives ``(min, max)`` on device inside the same
    program, so no extra host round-trip.  A deferred map chain fuses in.
    """
    bins = int(bins)
    if bins < 1:
        raise ValueError("bins must be >= 1, got %d" % bins)
    if range is not None:
        lo, hi = float(range[0]), float(range[1])
        if not (np.isfinite(lo) and np.isfinite(hi)):
            # numpy's rejection; NaN bounds would sail through the
            # ordering checks (all NaN comparisons are False) and return
            # garbage counts on the device path
            raise ValueError(
                "supplied range of [%s, %s] is not finite" % (lo, hi))
        if lo > hi:
            raise ValueError("range must satisfy min <= max, got %r"
                             % (range,))
        if lo == hi:
            # numpy expands an empty range by +-0.5 (constant-data case)
            lo, hi = lo - 0.5, hi + 0.5
    if b.mode == "local":
        counts, edges = np.histogram(np.asarray(b), bins=bins, range=range,
                                     density=density)
        return counts, edges

    from bolt_tpu.tpu.array import (_cached_jit, _chain_apply, _check_live)
    base, funcs = b._chain_parts()
    split = b.split
    mesh = b.mesh

    def build():
        def run(data):
            x = _chain_apply(funcs, split, data).reshape(-1)
            return jnp.histogram(x, bins=bins,
                                 range=None if range is None else (lo, hi))
        return jax.jit(run)

    fn = _cached_jit(("histogram", funcs, base.shape, str(base.dtype),
                      split, bins,
                      None if range is None else (lo, hi), mesh), build)
    counts, edges = (np.asarray(o) for o in jax.device_get(
        fn(_check_live(base))))
    if density:
        widths = np.diff(edges)
        counts = counts / widths / counts.sum()
    else:
        # jnp.histogram accumulates inexact ones; numpy returns int64 —
        # match the local backend exactly
        counts = counts.astype(np.int64)
    return counts, edges
