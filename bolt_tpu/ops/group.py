"""Segmented (grouped) reductions over the key axis.

The Spark ecosystem around the reference does this with
``reduceByKey``/``aggregateByKey`` — re-key records by a label, shuffle,
combine per group.  On TPU the whole thing is ONE compiled program:
``jax.ops.segment_*`` lowers to scatter-add/min/max, GSPMD inserts the
cross-shard combine, and the result comes back as a bolt array keyed by
group id.  Extension beyond the reference (``bolt/spark/array.py``
exposes no grouped reduction; symbol-level cite, SURVEY §0).
"""

import numpy as np

import jax
import jax.numpy as jnp

from bolt_tpu._compat import shard_map as _shard_map

_OPS = ("sum", "mean", "max", "min")


@jax.jit  # lint: allow(BLT101 one module-level program, keyed on ONE aval)
def _minmax_program(lab):
    # module-level jit: ONE compiled program per label aval (a per-call
    # inner @jax.jit would recompile every call — jit keys on function
    # identity; measured 1.09 s vs 0.11 s per segment_reduce on chip).
    # Deliberately NOT engine-routed: the engine key would have to carry
    # the aval this jit already keys on, for a two-scalar program with
    # nothing to donate or persist.
    return jnp.min(lab), jnp.max(lab)


def _label_minmax(labels):
    """``(min, max)`` of a device labels array as Python ints — ONE host
    sync of two scalars (the data itself never leaves the device)."""
    mn, mx = jax.device_get(_minmax_program(labels))
    return int(mn), int(mx)


def segment_reduce(b, labels, num_segments=None, op="sum", method=None,
                   precision=None):
    """Reduce the records of ``b`` (leading key axis) into groups given by
    ``labels``: record ``i`` joins group ``labels[i]``, and group ``g``'s
    result is the ``op``-combine of its records — the ``reduceByKey``
    analog, one compiled program.

    ``labels``: 1-d integers of length ``b.shape[0]``.  A host sequence /
    ndarray ships to the device once; a ``jax.Array`` (or a bolt TPU
    array) STAYS on device — range validation is one two-scalar sync, the
    label data itself never round-trips through the host.
    ``num_segments``: static group count (defaults to ``labels.max() + 1``
    — free on host labels, part of the same two-scalar sync on device
    labels); groups with no records get ``0`` for sum/mean and the
    dtype's identity (∓inf → the op's init) for max/min, matching
    ``jax.ops.segment_max/min``.  ``op='mean'`` on integer input promotes
    through the canonical float (float64 under x64, float32 on a
    production x64-off TPU) on BOTH backends, so the backends agree under
    either x64 setting.
    Returns a bolt array shaped ``(num_segments, *value_shape)`` with
    ``split=1`` (``mode='local'`` computes the same thing in NumPy).

    ``method``: ``None``/``"auto"`` (default) picks per a measured cost
    model; ``"scatter"`` forces the ``jax.ops.segment_*`` scatter
    combine; ``"matmul"`` forces the one-hot MXU form (sum/mean of
    floating data only).  The matmul form computes ``onehot(labels) @
    X`` — small segment counts turn the memory-latency-bound scatter
    into one MXU matmul (measured on chip, 2 GB f32, 256 segments:
    scatter 28 GB/s flat / 153 GB/s in the (8192, 1024, 64) layout;
    one-hot 321 GB/s at "highest", 449 GB/s under the "default"
    precision scope — sort+contiguous-scatter measured WORSE than plain
    scatter, 23 GB/s, and was dropped).  Products against a 0/1 matrix
    are exact, so "highest" matches the scatter combine to f32
    round-off (measured 2.4e-7 max rel).  Non-finite records would
    poison whole value columns through ``0 x NaN``, so the program
    guards with one fused ``isfinite`` test and falls back to the
    scatter combine at runtime when any record is non-finite —
    numpy/scatter semantics always.  ``precision=None`` resolves
    through the scoped policy (``bolt.precision``), pinned "highest".
    """
    if op not in _OPS:
        raise ValueError("op must be one of %s, got %r" % (_OPS, op))
    if method not in (None, "auto", "scatter", "matmul"):
        raise ValueError("method must be 'auto', 'scatter' or 'matmul', "
                         "got %r" % (method,))
    # op/dtype eligibility for the forced matmul form validates up front
    # — BEFORE the backend split, so both backends reject identically
    _float_in = np.issubdtype(np.dtype(b.dtype), np.floating) or (
        op == "mean" and np.issubdtype(np.dtype(b.dtype), np.integer))
    if method == "matmul" and (op not in ("sum", "mean") or not _float_in):
        raise ValueError(
            "method='matmul' serves sum/mean of real floating (or "
            "int-mean) data only, got op=%r dtype=%s" % (op, b.dtype))
    from bolt_tpu._precision import resolve
    pr = resolve(precision)
    from bolt_tpu.base import BoltArray
    if b.mode == "tpu":
        labels = b._coerce_bolt_operand(labels, "segment_reduce labels")
    elif isinstance(labels, BoltArray):
        labels = np.asarray(labels)
    device_labels = isinstance(labels, jax.Array) and b.mode == "tpu"
    if not device_labels:
        labels = np.asarray(labels)
    if labels.ndim != 1 or not np.issubdtype(
            np.dtype(labels.dtype), np.integer):
        raise ValueError("labels must be 1-d integers, got shape %s dtype %s"
                         % (labels.shape, labels.dtype))
    n = b.shape[0]
    if labels.shape[0] != n:
        raise ValueError("labels length %d != leading axis %d"
                         % (labels.shape[0], n))
    if device_labels:
        lmin, lmax = _label_minmax(labels) if labels.size else (0, -1)
    else:
        lmin = int(labels.min()) if labels.size else 0
        lmax = int(labels.max()) if labels.size else -1
    if labels.size and lmin < 0:
        raise ValueError("labels must be non-negative")
    if num_segments is None:
        num_segments = lmax + 1 if labels.size else 0
    num_segments = int(num_segments)
    if labels.size and lmax >= num_segments:
        raise ValueError("label %d out of range for num_segments=%d"
                         % (lmax, num_segments))

    if b.mode == "local":
        x = np.asarray(b)
        vshape = x.shape[1:]
        if op in ("sum", "mean"):
            if op == "mean" and not np.issubdtype(x.dtype, np.floating):
                # mean of ints is floating — promote through the CANONICAL
                # float (f64 under x64, f32 otherwise) so this oracle and
                # the TPU path return the same dtype under either setting
                x = x.astype(jax.dtypes.canonicalize_dtype(np.float64))
            out = np.zeros((num_segments,) + vshape, x.dtype)
            np.add.at(out, labels, x)
            if op == "mean":
                cnt = np.bincount(labels, minlength=num_segments)
                out = out / np.maximum(cnt, 1).reshape(
                    (num_segments,) + (1,) * len(vshape)).astype(x.dtype)
        else:
            if np.issubdtype(x.dtype, np.floating):
                init = -np.inf if op == "max" else np.inf
            else:                           # empty-group identity for ints
                info = np.iinfo(x.dtype)
                init = info.min if op == "max" else info.max
            out = np.full((num_segments,) + vshape, init, x.dtype)
            ufunc = np.maximum if op == "max" else np.minimum
            ufunc.at(out, labels, x)
        from bolt_tpu.local.array import BoltArrayLocal
        return BoltArrayLocal(out)

    from bolt_tpu.tpu.array import (BoltArrayTPU, _cached_jit, _chain_apply,
                                    _check_live, _constrain)
    base, funcs = b._chain_parts()
    split = b.split
    mesh = b.mesh

    # cost-model gate for the one-hot MXU form (docstring numbers):
    #   matmul ~ 2 * nseg * size flops at the MXU's effective rate per
    #   precision mode, PLUS the materialised (nseg, n) one-hot's own
    #   HBM traffic; scatter ~ bytes at its measured ~150 GB/s upper
    #   band.  Only sum/mean of real floating data qualify (ints must
    #   stay exact, complex has no bf16 path, max/min cannot matmul).
    #   Thin-value/many-record inputs make the one-hot the dominant
    #   tensor, so it is capped at the data's own size (and demand-
    #   checked) before the flop model even gets a vote.
    item = np.dtype(b.dtype).itemsize
    oh_item = 2 if np.dtype(b.dtype) == np.float32 else item
    oh_bytes = float(num_segments) * n * oh_item
    data_bytes = float(b.size) * item
    mxu_eff = {"default": 1.0e14, "high": 6.0e13, "highest": 3.0e13}[pr]
    est_matmul = (2.0 * num_segments * b.size / mxu_eff
                  + oh_bytes / 6.0e11)
    est_scatter = data_bytes / 1.5e11
    if method == "matmul" and n > 0:
        from bolt_tpu.tpu.array import hbm_check
        hbm_check("segment_reduce matmul",
                  int(data_bytes + oh_bytes
                      + num_segments * (b.size // max(n, 1)) * item),
                  "input + one-hot + output")
    use_matmul = (method == "matmul" or (
        method in (None, "auto") and op in ("sum", "mean") and _float_in
        and num_segments > 0 and oh_bytes <= data_bytes
        and est_matmul < est_scatter)) and n > 0

    def build():
        seg = {"sum": jax.ops.segment_sum, "mean": jax.ops.segment_sum,
               "max": jax.ops.segment_max, "min": jax.ops.segment_min}[op]

        def promote(flat):
            if op == "mean" and not jnp.issubdtype(flat.dtype,
                                                   jnp.floating):
                # mean of ints is floating (f64 under x64, like numpy)
                return flat.astype(
                    jax.dtypes.canonicalize_dtype(np.float64))
            return flat

        def scatter_out(flat, lab):
            out = seg(flat, lab, num_segments=num_segments)
            return mean_divide(out, lab) if op == "mean" else out

        def matmul_sum(flat, lab):
            # onehot(labels) @ X: 0/1 products are exact, so "highest"
            # matches the scatter combine to f32 round-off; GSPMD
            # shards the contraction over the key axis and all-reduces
            # the (nseg, V) partials over ICI.  The one-hot rides bf16
            # against f32 data (0/1 is exact in bf16, and the narrow
            # operand halves its MXU passes — the measured-321-GB/s
            # configuration); other dtypes keep their own width.
            oh_dt = jnp.bfloat16 if flat.dtype == jnp.float32 \
                else flat.dtype
            oh = (lab[None, :] ==
                  jnp.arange(num_segments, dtype=jnp.int32)[:, None]
                  ).astype(oh_dt)
            v2d = flat.reshape((n, -1))
            out = jax.lax.dot_general(
                oh, v2d, (((1,), (0,)), ((), ())), precision=pr,
                preferred_element_type=flat.dtype)
            return out.reshape((num_segments,) + flat.shape[1:])

        def mean_divide(out, lab):
            cnt = jax.ops.segment_sum(
                jnp.ones((n,), out.dtype), lab,
                num_segments=num_segments)
            return out / jnp.maximum(cnt, 1).reshape(
                (num_segments,) + (1,) * (out.ndim - 1))

        def run(data, lab):
            # records = axis-0 groups, like the labels contract; further
            # key axes just ride along in the value block (the local
            # oracle path flattens identically)
            lab = lab.astype(jnp.int32)
            flat = promote(_chain_apply(funcs, split, data))
            if use_matmul:
                # 0 x NaN poisons whole value columns through the
                # one-hot, so a non-finite RECORD always surfaces as a
                # non-finite OUTPUT entry (and a finite-input partial-
                # sum overflow surfaces as Inf/NaN) — checking the
                # small (nseg, V) RESULT costs ~nothing where a
                # pre-pass over the input would re-read all of HBM
                # serially (measured 9.0 -> 6.9 ms on the perf family).
                # Any hit recomputes with the exact scatter combine
                # (numpy non-finite semantics) at runtime.
                s = matmul_sum(flat, lab)
                ok = jnp.all(jnp.isfinite(s))
                out = jax.lax.cond(
                    ok, lambda f, l, sm: sm,
                    lambda f, l, sm: seg(f, l,
                                         num_segments=num_segments),
                    flat, lab, s)
                if op == "mean":
                    out = mean_divide(out, lab)
            else:
                out = scatter_out(flat, lab)
            return _constrain(out, mesh, 1)
        return jax.jit(run)

    # labels is a traced argument (its length is pinned by base.shape), so
    # distinct label vectors REUSE one compiled program — never key on
    # label content; device labels pass through untouched (the int32 cast
    # happens inside the program — no host round-trip)
    fn = _cached_jit(("segreduce", op, funcs, base.shape, str(base.dtype),
                      split, num_segments, mesh, use_matmul,
                      pr if use_matmul else None), build)
    lab = labels if device_labels else jnp.asarray(labels, dtype=jnp.int32)
    out = fn(_check_live(base), lab)
    return BoltArrayTPU(out, 1, mesh)


def _topk_desc(xp, moved, k):
    """Largest ``k`` along the LAST axis, descending, with
    ``lax.top_k``'s exact tie/NaN semantics, for either array module
    (``np`` on the oracle, ``jnp`` on device) — ONE algorithm on both
    backends, and the formulation GSPMD partitions without gathering
    (``lax.top_k`` itself all-gathers a sharded operand; a stable
    argsort along an unsharded last axis is collective-free, and along
    a sharded axis lowers to all-to-all — see tests/test_lowering.py).

    Descending order WITHOUT negating (negation wraps unsigned/INT_MIN
    and rejects bools): stable-ascending-argsort the index-reversed
    array (ties there resolve to the HIGHER original index), map back,
    reverse — descending, ties to the LOWER index, NaNs first
    (largest)."""
    L = moved.shape[-1]
    if xp is np:
        idx_rev = np.argsort(moved[..., ::-1], axis=-1, kind="stable")
    else:
        idx_rev = xp.argsort(moved[..., ::-1], axis=-1, stable=True)
    desc = (L - 1 - idx_rev)[..., ::-1]
    idx = desc[..., :k]
    return xp.take_along_axis(moved, idx, axis=-1), idx


def topk(b, k, axis=-1):
    """Largest ``k`` values (descending) and their indices along ``axis``
    — ``jax.lax.top_k`` semantics, one compiled program; returns
    ``(values, indices)`` bolt arrays whose ``axis`` dimension becomes
    ``k``.  Ties keep the lower index first, like ``lax.top_k`` (numpy
    has no direct analog; ``argpartition`` leaves ties unordered).
    ``mode='local'`` computes the same thing in NumPy (including
    ``lax.top_k``'s NaN-is-largest ordering)."""
    from numbers import Integral
    if not isinstance(k, Integral):
        raise TypeError("k must be an integer, got %r" % (k,))
    k = int(k)
    ndim = b.ndim
    if not isinstance(axis, (int, np.integer)):
        raise TypeError("axis must be an integer, got %r" % (axis,))
    axis = int(axis)
    if axis < 0:
        axis += ndim
    if axis < 0 or axis >= ndim:
        raise ValueError("axis out of range for %d-d array" % ndim)
    if not 1 <= k <= b.shape[axis]:
        raise ValueError("k=%d out of range for axis of size %d"
                         % (k, b.shape[axis]))

    if b.mode == "local":
        x = np.asarray(b)
        moved = np.moveaxis(x, axis, -1)
        vals, idx = _topk_desc(np, moved, k)
        from bolt_tpu.local.array import BoltArrayLocal
        return (BoltArrayLocal(np.moveaxis(vals, -1, axis)),
                BoltArrayLocal(np.moveaxis(idx, -1, axis)))

    from bolt_tpu.tpu.array import (_CHUNK_MAX_BYTES, BoltArrayTPU,
                                    _cached_jit, _chain_apply, _check_live,
                                    _constrain, hbm_check)
    base, funcs = b._chain_parts()
    split = b.split
    mesh = b.mesh
    # the axis keeps its key/value role (its size becomes k; a
    # non-dividing key size just falls back to replication in the spec)

    # memory model: _topk_desc materialises the (possibly transposed)
    # operand, its reversed view, and an input-sized argsort index
    # array; at HBM scale a non-last ``axis`` is bounded by slabbing
    # along another axis (outputs are k-sized — small — so the
    # reassembly concatenate is cheap).  VERDICT r2 weak-4.
    idx_item = np.dtype(jax.dtypes.canonicalize_dtype(np.int64)).itemsize
    in_bytes = int(np.prod(b.shape)) * np.dtype(b.dtype).itemsize
    idx_bytes = int(np.prod(b.shape)) * idx_item
    if axis != ndim - 1 and in_bytes > _CHUNK_MAX_BYTES:
        out = _topk_chunked(b, k, axis, in_bytes)
        if out is not None:
            return out
    hbm_check("topk", 2 * in_bytes + idx_bytes,
              "input + reversed/transposed copy + argsort index array")

    def build():
        def run(data):
            x = _chain_apply(funcs, split, data)
            moved = jnp.moveaxis(x, axis, -1)
            vals, idx = _topk_desc(jnp, moved, k)
            return (_constrain(jnp.moveaxis(vals, -1, axis), mesh, split),
                    _constrain(jnp.moveaxis(idx, -1, axis), mesh, split))
        return jax.jit(run)

    vals, idx = _cached_jit(
        ("topk", funcs, base.shape, str(base.dtype), split, axis, k, mesh),
        build)(_check_live(base))
    return (BoltArrayTPU(vals, split, mesh),
            BoltArrayTPU(idx, split, mesh))


def _topk_chunked(b, k, axis, in_bytes):
    """HBM-bounded topk over a non-last axis: slab along another axis so
    the transposed copy lax.top_k needs never exceeds a slab; per-slab
    (k-sized) results concatenate back along the slab axis.  Returns
    None when no other axis can carry the slabbing."""
    import jax
    import jax.numpy as jnp
    from bolt_tpu.tpu.array import (BoltArrayTPU, _cached_jit, _constrain,
                                    hbm_check, slab_plan)
    plan = slab_plan(b.shape, axis, in_bytes)
    if plan is None:
        return None
    cax, pairs = plan
    slab_bytes = in_bytes // len(pairs)
    idx_item = np.dtype(jax.dtypes.canonicalize_dtype(np.int64)).itemsize
    hbm_check("topk", in_bytes + 2 * slab_bytes
              + (slab_bytes // np.dtype(b.dtype).itemsize) * idx_item,
              "input + per-slab transposed copy + per-slab argsort index")
    data = b._data                          # chain materialises once
    mesh, split = b.mesh, b.split
    parts = []
    for s0, s1 in pairs:

        def slab_build(s0=s0, s1=s1):
            def run(d):
                slab = jax.lax.slice_in_dim(d, s0, s1, axis=cax)
                moved = jnp.moveaxis(slab, axis, -1)
                vals, idx = _topk_desc(jnp, moved, k)
                return (jnp.moveaxis(vals, -1, axis),
                        jnp.moveaxis(idx, -1, axis))
            return jax.jit(run)

        parts.append(_cached_jit(
            ("topk-slab", data.shape, str(data.dtype), split, axis, k,
             s0, s1, cax, mesh), slab_build)(data))

    def cat_build():
        def run(vs, ids):
            return (_constrain(jnp.concatenate(vs, axis=cax), mesh, split),
                    _constrain(jnp.concatenate(ids, axis=cax), mesh, split))
        return jax.jit(run)

    vals, idx = _cached_jit(
        ("topk-cat", data.shape, str(data.dtype), split, axis, k, cax,
         tuple(pairs), mesh), cat_build)(
        [p[0] for p in parts], [p[1] for p in parts])
    return (BoltArrayTPU(vals, split, mesh),
            BoltArrayTPU(idx, split, mesh))


def unique(b, return_counts=False):
    """``numpy.unique`` over ALL elements (flattened): sorted unique
    values as a host ndarray, optionally with per-value counts.

    XLA needs static shapes, so the device work is two programs (the
    filter two-phase pattern, SURVEY §7 hard part 1): sort + first-
    occurrence mask + count, one scalar sync, then a ``k``-shaped gather
    of the unique values (and counts as index differences) — the host
    never receives more than the ``k`` uniques.  Like modern numpy, all
    NaNs collapse to a single entry (they sort together at the end).

    Memory model: the sorted copy + mask is a ~1.25× input transient; at
    HBM scale (input > ``_CHUNK_MAX_BYTES``) the op switches to a
    CHUNKED path — per-chunk sort/mask/gather (transients bounded by the
    chunk size) with an exact host-side merge of the per-chunk uniques
    and counts — so a 10 GB ``unique`` never doubles HBM (VERDICT r2
    weak-4).
    """
    if b.mode == "local":
        return np.unique(np.asarray(b), return_counts=return_counts)

    from bolt_tpu.tpu.array import (_CHUNK_MAX_BYTES, _cached_jit,
                                    _chain_apply, _check_live)
    n = int(np.prod(b.shape))
    if n == 0:
        empty = np.empty(0, np.dtype(b.dtype))
        return (empty, np.empty(0, np.int64)) if return_counts else empty
    # the sharded attempt runs BEFORE the chain parts are captured: it
    # may materialise the chain (its gates need the concrete sharding),
    # and capturing first would make the fallback re-run the chain
    sharded = _unique_sharded(b, return_counts)
    if sharded is not None:
        return sharded
    if n * np.dtype(b.dtype).itemsize > _CHUNK_MAX_BYTES:
        return _unique_chunked(b, return_counts)
    base, funcs = b._chain_parts()
    split = b.split
    mesh = b.mesh

    sorted_, mask, cnt = _cached_jit(
        ("unique-sort", funcs, base.shape, str(base.dtype), split, mesh),
        lambda: jax.jit(_unique_phase1(funcs, split, None,
                                       None)))(_check_live(base))
    k = int(jax.device_get(cnt))               # the one unavoidable sync

    # n is the chain-OUTPUT element count (a shape-changing map can alter
    # it), so the key carries funcs and n like every other chain consumer
    out = jax.device_get(_cached_jit(
        ("unique-gather", funcs, base.shape, str(base.dtype), split, n, k,
         return_counts, mesh),
        lambda: jax.jit(_unique_phase2(n, k, return_counts)))(sorted_, mask))
    uniq = np.asarray(out[0])
    if return_counts:
        return uniq, np.asarray(out[1]).astype(np.int64)
    return uniq


def _sort_mask(flat):
    """Sorted values, first-occurrence mask — with numpy's NaN collapse:
    sorted NaNs are contiguous at the end, so "both NaN" marks
    duplicates — and the mask count.  The ONE mask semantics shared by
    the whole-array, chunked, and shard-local unique paths."""
    flat = jnp.sort(flat)
    neq = flat[1:] != flat[:-1]
    if jnp.issubdtype(flat.dtype, jnp.floating):
        neq &= ~(jnp.isnan(flat[1:]) & jnp.isnan(flat[:-1]))
    mask = jnp.concatenate([jnp.ones(1, bool), neq])
    return flat, mask, jnp.sum(mask, dtype=jnp.int32)


def _gather_uniques(s, msk, m, size, return_counts):
    """Gather ``size`` unique values (first-occurrence indices) out of
    an ``m``-element sorted piece, with counts as index differences;
    pad gathers clip to the last element and the host trims.  Counts
    use the canonical int on device (int32 when x64 is off — no
    warning); the host widens to int64 after the fetch.  Shared by
    every unique path."""
    idx = jnp.nonzero(msk, size=size, fill_value=m)[0]
    uniq = jnp.take(s, idx, axis=0, mode="clip")
    if not return_counts:
        return (uniq,)       # skip the counts work and their transfer
    ends = jnp.concatenate([idx[1:], jnp.asarray([m], idx.dtype)])
    return uniq, (ends - idx).astype(
        jax.dtypes.canonicalize_dtype(np.int64))


def _merge_unique_parts(vals_parts, cnt_parts, return_counts):
    """Exact host merge of per-piece uniques (+counts): the union of
    piece uniques is the global unique set and counts add (np.unique's
    NaN collapse maps every piece's NaN to one slot).  Shared by the
    chunked and shard-local paths."""
    allv = np.concatenate(vals_parts)
    if not return_counts:
        return np.unique(allv)
    uniq, inv = np.unique(allv, return_inverse=True)
    tot = np.zeros(len(uniq), np.int64)
    np.add.at(tot, inv, np.concatenate(cnt_parts))
    return uniq, tot


def _unique_phase1(funcs, split, start, stop):
    """Phase-1 traced body: :func:`_sort_mask` over (a ``[start:stop)``
    slice of) the flattened chain output.  Returns the UNJITTED
    callable — the engine builder at the call site jits it, so
    compilation stays on the engine's counted AOT path (lint BLT101)."""
    from bolt_tpu.tpu.array import _chain_apply

    def run(d):
        flat = _chain_apply(funcs, split, d).reshape(-1)
        if start is not None:
            flat = jax.lax.slice_in_dim(flat, start, stop)
        return _sort_mask(flat)
    return run


def _unique_phase2(m, size, return_counts):
    """Phase-2 traced body: :func:`_gather_uniques` (unjitted — the
    engine builder at the call site jits it)."""
    def run(s, msk):
        return _gather_uniques(s, msk, m, size, return_counts)
    return run


# bincount accumulates per-chunk below this element count when the
# canonical int is int32 (x64 off), so no bin can reach 2**31 inside one
# device program; chunk partials combine in host int64.  None = automatic
# (engages only when x64 is off AND the array is big enough to wrap);
# tests set it small to force the chunked path.
_BINCOUNT_CHUNK = None


def _unique_sharded(b, return_counts):
    """Shard-local ``unique`` for a multi-device array: ``shard_map``
    sorts and masks each shard's OWN block (a global sort order is not
    needed — any partition of the elements works for unique), per-shard
    counts sync in one fetch, a second shard-local program gathers each
    shard's uniques padded to a power of two, and the host merges
    exactly — ZERO device collectives, where GSPMD's global 1-d sort
    would all-gather the whole operand onto every device (the round-3
    lowering probe).

    Returns None (caller keeps the single-program / chunked paths) for
    the layouts the simple formulation doesn't cover: single device,
    multi-process (the per-shard outputs must be addressable), a
    replicated dimension (per-shard counts would multiply), a
    non-NamedSharding, or shards too big for their local sort transient.
    """
    from jax.sharding import NamedSharding, PartitionSpec
    from bolt_tpu.parallel import multihost as _mh
    from bolt_tpu.tpu.array import _CHUNK_MAX_BYTES, _cached_jit
    # cheap gates FIRST — they must not materialise a deferred chain
    # just to decline (single-device / multi-process layouts)
    if b.mesh is None or b.mesh.size <= 1 or _mh.process_count() > 1:
        return None
    data = b._data                          # chain materialises once
    sharding = data.sharding
    if not isinstance(sharding, NamedSharding):
        return None
    mesh = sharding.mesh
    if not data.is_fully_addressable:
        return None
    used = []
    for dim, entry in enumerate(sharding.spec):
        if entry is None:
            continue
        names = entry if isinstance(entry, tuple) else (entry,)
        ways = int(np.prod([mesh.shape[u] for u in names]))
        if data.shape[dim] % ways != 0:
            return None                      # shard_map needs even splits
        used.extend(names)
    nshards = int(np.prod([mesh.shape[u] for u in used])) if used else 1
    if nshards != mesh.size or nshards <= 1:
        return None                          # replicated somewhere
    local_elems = data.size // nshards
    if local_elems == 0 \
            or local_elems * data.dtype.itemsize > _CHUNK_MAX_BYTES:
        return None
    spec = sharding.spec
    out_spec = PartitionSpec(tuple(used))

    def p1_build():
        def local(blk):
            flat, mask, cnt = _sort_mask(blk.reshape(-1))
            return flat[None], mask[None], cnt[None]
        return jax.jit(_shard_map(
            local, mesh=mesh, in_specs=spec,
            out_specs=(out_spec, out_spec, out_spec)))

    sorted_, mask, cnt = _cached_jit(
        ("unique-shard-sort", data.shape, str(data.dtype), spec, mesh),
        p1_build)(data)
    counts = np.asarray(jax.device_get(cnt))   # the one sync
    kpad = 1 << max(0, (int(counts.max()) - 1).bit_length())

    def p2_build():
        def gather(s_ref, m_ref):
            out = _gather_uniques(s_ref[0], m_ref[0], s_ref.shape[1],
                                  kpad, return_counts)
            return tuple(o[None] for o in out)
        return jax.jit(_shard_map(
            gather, mesh=mesh, in_specs=(out_spec, out_spec),
            out_specs=(out_spec,) * (2 if return_counts else 1)))

    out = jax.device_get(_cached_jit(
        ("unique-shard-gather", data.shape, str(data.dtype), spec, kpad,
         return_counts, mesh), p2_build)(sorted_, mask))
    vals_parts = [np.asarray(out[0][i][:int(counts[i])])
                  for i in range(nshards)]
    cnt_parts = [np.asarray(out[1][i][:int(counts[i])]).astype(np.int64)
                 for i in range(nshards)] if return_counts else None
    return _merge_unique_parts(vals_parts, cnt_parts, return_counts)


def _unique_chunked(b, return_counts):
    """HBM-bounded ``unique``: sort/mask/count/gather one
    ``_CHUNK_MAX_BYTES`` slice of the flattened array at a time (device
    transients never exceed ~2.25× one chunk), then merge the per-chunk
    uniques and counts EXACTLY on host — the union of per-chunk uniques
    is the global unique set, and counts add.  The per-chunk gather pads
    its size to the next power of two so the compiled-program count
    stays logarithmic in the unique count, not linear in chunks."""
    import jax
    from bolt_tpu.tpu.array import _CHUNK_MAX_BYTES, _cached_jit
    data = b._data                          # chain materialises once
    mesh = b.mesh
    n = int(np.prod(data.shape))
    itemsize = np.dtype(data.dtype).itemsize
    chunk = max(1, _CHUNK_MAX_BYTES // itemsize)
    vals_parts, cnt_parts = [], []
    for start in range(0, n, chunk):
        stop = min(start + chunk, n)
        m = stop - start

        sorted_, mask, cnt = _cached_jit(
            ("unique-chunk-sort", data.shape, str(data.dtype), start,
             stop, mesh),
            lambda start=start, stop=stop: jax.jit(_unique_phase1(
                (), 0, start, stop)))(data)
        k = int(jax.device_get(cnt))
        kpad = 1 << max(0, (k - 1).bit_length())

        out = jax.device_get(_cached_jit(
            ("unique-chunk-gather", str(data.dtype), m, kpad,
             return_counts, mesh),
            lambda m=m, kpad=kpad: jax.jit(_unique_phase2(
                m, kpad, return_counts)))(sorted_, mask))
        vals_parts.append(np.asarray(out[0])[:k])
        if return_counts:
            cnt_parts.append(np.asarray(out[1])[:k].astype(np.int64))
    return _merge_unique_parts(vals_parts,
                               cnt_parts if return_counts else None,
                               return_counts)


def bincount(b, minlength=0):
    """``numpy.bincount`` over ALL elements of an integer bolt array
    (flattened, like numpy), as one compiled program; returns a host
    int64 ndarray of length ``max(minlength, max(b) + 1)``.  The length
    must be static for XLA, so a device-side max costs one scalar sync
    when ``minlength`` doesn't already cover it.  Counts accumulate in
    the canonical int; when that is int32 (x64 off, the production-TPU
    default) arrays big enough for a single bin to pass 2**31−1 are
    counted in chunks whose int32 partials combine in host int64 — the
    result is exact at any size, matching the local backend."""
    if not np.issubdtype(np.dtype(b.dtype), np.integer):
        raise TypeError("bincount requires an integer array, got %s"
                        % (b.dtype,))
    minlength = int(minlength)
    if minlength < 0:
        raise ValueError("'minlength' must not be negative")
    if b.size == 0:
        return np.zeros(minlength, np.int64)   # numpy's empty contract
    if b.mode == "local":
        return np.bincount(np.asarray(b).reshape(-1), minlength=minlength)

    from bolt_tpu.tpu.array import _cached_jit, _chain_apply, _check_live
    base, funcs = b._chain_parts()
    split = b.split
    mesh = b.mesh

    def minmax_build():
        def mm(data):
            x = _chain_apply(funcs, split, data).reshape(-1)
            return jnp.min(x), jnp.max(x)
        return jax.jit(mm)

    mn, mx = jax.device_get(_cached_jit(
        ("bincount-minmax", funcs, base.shape, str(base.dtype), split, mesh),
        minmax_build)(_check_live(base)))
    if int(mn) < 0:
        raise ValueError("bincount requires non-negative values")
    length = max(minlength, int(mx) + 1)

    n_elems = int(np.prod(b.shape))
    chunk = _BINCOUNT_CHUNK
    if chunk is None and jax.dtypes.canonicalize_dtype(np.int64) != np.int64:
        chunk = (1 << 31) - (1 << 20)
    if chunk is not None and n_elems > chunk:
        # x32 wraparound guard: each device program counts < 2**31
        # elements (its int32 per-bin partial cannot wrap); partials
        # combine exactly in host int64.  Chunk starts stay STATIC —
        # dynamic-start slices of sharded operands make GSPMD all-gather
        # the whole array (BASELINE.md) — so it is one program per chunk;
        # at the default ~2**31 chunk a 16 GB chip holds at most a
        # handful of chunks.
        total = np.zeros(length, np.int64)
        # materialise any deferred chain ONCE (a per-chunk program would
        # re-run the whole chain before slicing its window)
        data = b._data
        for start in range(0, n_elems, chunk):
            stop = min(start + chunk, n_elems)

            def chunk_build(start=start, stop=stop):
                def run(d):
                    x = d.reshape(-1)
                    return jax.ops.segment_sum(
                        jnp.ones(stop - start,
                                 jax.dtypes.canonicalize_dtype(np.int64)),
                        jax.lax.slice_in_dim(x, start, stop),
                        num_segments=length)
                return jax.jit(run)

            part = _cached_jit(
                ("bincount-chunk", data.shape, str(data.dtype),
                 length, start, stop, mesh),
                chunk_build)(data)
            total += np.asarray(jax.device_get(part)).astype(np.int64)
        return total

    def build():
        def run(data):
            x = _chain_apply(funcs, split, data).reshape(-1)
            return jax.ops.segment_sum(
                jnp.ones_like(x, dtype=jax.dtypes.canonicalize_dtype(
                    np.int64)), x, num_segments=length)
        return jax.jit(run)

    counts = _cached_jit(("bincount", funcs, base.shape, str(base.dtype),
                          split, length, mesh), build)(_check_live(base))
    return np.asarray(jax.device_get(counts)).astype(np.int64)
