from bolt_tpu.ops.kernels import fused_map_reduce, fused_stats
from bolt_tpu.ops.linalg import (corrcoef, cov, jacobi_eigh, lstsq, pca,
                                 svdvals, tallskinny_pca, tallskinny_svd,
                                 tsqr)
from bolt_tpu.ops.overlap import convolve, gaussian, map_overlap, smooth

__all__ = ["convolve", "corrcoef", "cov", "fused_map_reduce",
           "fused_stats", "gaussian", "jacobi_eigh", "lstsq",
           "map_overlap", "pca", "smooth", "svdvals", "tallskinny_pca",
           "tallskinny_svd", "tsqr"]
