from bolt_tpu.ops.group import bincount, segment_reduce, topk, unique
from bolt_tpu.ops.hist import histogram
from bolt_tpu.ops.kernels import (fused_map_reduce, fused_stats,
                                  fused_welford, sepfilter1d)
from bolt_tpu.ops.linalg import (corrcoef, cov, jacobi_eigh, lstsq, pca,
                                 svdvals, tallskinny_pca, tallskinny_svd,
                                 tsqr)
from bolt_tpu.ops.overlap import (convolve, gaussian, map_overlap,
                                  median_filter, smooth)
from bolt_tpu.ops.series import (center, crosscorr, detrend, fourier,
                                 normalize, zscore)

__all__ = ["bincount", "center", "convolve", "corrcoef", "cov",
           "crosscorr", "segment_reduce", "topk", "unique",
           "detrend", "fourier", "fused_map_reduce", "fused_stats",
           "fused_welford", "gaussian", "sepfilter1d", "histogram", "jacobi_eigh",
           "lstsq", "map_overlap",
           "median_filter", "normalize", "pca", "smooth", "svdvals",
           "tallskinny_pca", "tallskinny_svd", "tsqr", "zscore"]
