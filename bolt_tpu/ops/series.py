"""Per-record time-series transforms: detrend, z-score.

The reference ecosystem's TimeSeries workloads (Thunder: records keyed by
pixel/channel, values = a time axis) detrend and standardise every record
before analysis.  Here each transform is a traceable per-record ``map`` —
it DEFERS like any map and fuses into the next action, so
``zscore(detrend(b)).stats()`` is one compiled pass over HBM.  Both
backends run the same math (NumPy locally — the oracle).

Polynomial detrending is two thin matmuls per record against the
precomputed Vandermonde ``A`` and its pseudo-inverse (``v - A @
(pinv(A) @ v)``) — MXU-shaped work, built host-side once per
(length, order).
"""

from functools import lru_cache

import numpy as np
import jax.numpy as jnp

from bolt_tpu._precision import resolve as _resolve


def _value_axis(b, axis):
    """Resolve ONE value-axis index (relative to the value group)."""
    split = b.split if b.mode == "tpu" else 1
    nv = b.ndim - split
    ax = int(axis)
    if ax < 0:
        ax += nv
    if ax < 0 or ax >= nv:
        raise ValueError(
            "value axis %r out of range for %d value axes" % (axis, nv))
    return ax, split


def _apply_map(b, func):
    """Per-record map on either backend (axis = the array's key axes)."""
    if b.mode == "tpu":
        return b.map(func, axis=tuple(range(b.split)))
    return b.map(func, axis=(0,))


def detrend(b, order=1, axis=0):
    """Remove a least-squares polynomial trend of ``order`` along the
    value axis ``axis`` of every record.

    ``order=0`` removes the mean, ``order=1`` a linear trend, etc.  The
    fit is exact (normal equations via ``pinv``, precomputed host-side),
    and the subtraction is one matmul along the axis inside the fused
    per-record program.
    """
    order = int(order)
    if order < 0:
        raise ValueError("order must be >= 0, got %d" % order)
    ax, split = _value_axis(b, axis)
    length = b.shape[split + ax]
    if length <= order:
        raise ValueError(
            "axis of length %d cannot fit a degree-%d trend" % (length, order))
    return _apply_map(b, _detrend_fn(length, order, ax))


@lru_cache(maxsize=256)
def _detrend_fn(length, order, ax):
    # residual = v - A @ (pinv(A) @ v): two THIN matmuls (L x (order+1)),
    # O(L * order) per record — never materialise the (L, L) projector,
    # which for a 40k-sample axis would be ~13 GB.  Memoised so repeated
    # detrend calls return the SAME callable and the jit cache (keyed on
    # function identity) hits instead of recompiling.
    t = np.linspace(-1.0, 1.0, length)
    a_mat = np.vander(t, order + 1, increasing=True)
    pinv_a = np.linalg.pinv(a_mat)

    def f(v):
        xp = np if isinstance(v, np.ndarray) else jnp
        # promote to float: casting the fit matrices to an int dtype
        # would truncate them to zeros and silently return zeros
        dt = xp.promote_types(v.dtype, xp.float32)
        a_ = xp.asarray(a_mat, dtype=dt)
        p_ = xp.asarray(pinv_a, dtype=dt)
        moved = xp.moveaxis(v.astype(dt), ax, -1)
        if xp is jnp:
            # deliberate pin through the resolver (explicit always wins):
            # the fit matrices are f32/f64 host constants — a bf16 pass
            # here would dominate the detrend residual
            coef = jnp.matmul(moved, p_.T, precision=_resolve("highest"))
            fit = jnp.matmul(coef, a_.T, precision=_resolve("highest"))
        else:
            coef = moved @ p_.T
            fit = coef @ a_.T
        return xp.moveaxis(moved - fit, -1, ax)

    return f


def zscore(b, axis=0, ddof=0, epsilon=0.0):
    """Standardise every record along the value axis ``axis``:
    ``(v - mean) / (std + epsilon)``.

    ``ddof`` selects population (0, default — the reference StatCounter
    convention) or sample (1) standard deviation; ``epsilon`` guards
    constant records (otherwise they divide by zero, matching numpy's
    nan/inf behavior).
    """
    ax, _ = _value_axis(b, axis)
    return _apply_map(b, _zscore_fn(ax, int(ddof), float(epsilon)))


@lru_cache(maxsize=256)
def _zscore_fn(ax, ddof, epsilon):
    def f(v):
        xp = np if isinstance(v, np.ndarray) else jnp
        mu = xp.mean(v, axis=ax, keepdims=True)
        sd = xp.std(v, axis=ax, ddof=ddof, keepdims=True)
        return (v - mu) / (sd + epsilon)
    return f


def center(b, axis=0):
    """Subtract the per-record mean along the value axis ``axis``."""
    ax, _ = _value_axis(b, axis)
    return _apply_map(b, _center_fn(ax))


@lru_cache(maxsize=256)
def _center_fn(ax):
    def f(v):
        xp = np if isinstance(v, np.ndarray) else jnp
        return v - xp.mean(v, axis=ax, keepdims=True)
    return f


def crosscorr(b, signal, lag=0, axis=0, epsilon=0.0):
    """Per-record normalised cross-correlation with a reference
    ``signal`` along the value axis ``axis`` (the Thunder
    ``TimeSeries.crossCorr`` workload).

    For each integer shift ``k`` in ``[-lag, lag]`` the Pearson
    correlation between ``v[t]`` and ``signal[t - k]`` is computed over
    their overlapping window, so the axis of length ``L`` is replaced by
    ``2*lag + 1`` correlation values (``lag=0`` gives each record's
    plain correlation with the signal).  A deferred map on either
    backend; the shift loop is static (``lag`` is small), one fused
    program on TPU.  ``epsilon`` is added to the normaliser to guard
    constant records/windows (otherwise they divide 0/0 to NaN, like
    ``zscore`` without its epsilon).
    """
    lag = int(lag)
    if lag < 0:
        raise ValueError("lag must be >= 0, got %d" % lag)
    ax, split = _value_axis(b, axis)
    length = b.shape[split + ax]
    sig = np.asarray(signal, dtype=np.float64).ravel()
    if sig.shape[0] != length:
        raise ValueError(
            "signal length %d does not match axis length %d"
            % (sig.shape[0], length))
    if lag > length - 2:
        raise ValueError(
            "lag %d needs at least 2 overlapping samples on an axis of "
            "length %d (Pearson r of a single sample is undefined)"
            % (lag, length))
    return _apply_map(
        b, _crosscorr_fn(sig.tobytes(), length, lag, ax, float(epsilon)))


@lru_cache(maxsize=128)
def _crosscorr_fn(sig_bytes, length, lag, ax, epsilon):
    # per-shift signal statistics are pure functions of the host-side
    # signal: centre each window and take its sum-of-squares in float64
    # here, so the traced program only does the record-side math.
    # Memoised by signal CONTENT so repeated calls hit the jit cache.
    sig = np.frombuffer(sig_bytes, dtype=np.float64)
    windows = []
    for k in range(-lag, lag + 1):
        ssub = sig[:length - k] if k >= 0 else sig[-k:]
        sc = ssub - ssub.mean()
        windows.append((k, sc, float(np.sum(sc * sc))))

    def f(v):
        xp = np if isinstance(v, np.ndarray) else jnp
        dt = xp.promote_types(v.dtype, xp.float32)
        moved = xp.moveaxis(v.astype(dt), ax, -1)
        outs = []
        for k, sc_np, sc_ss in windows:
            a = moved[..., k:] if k >= 0 else moved[..., :length + k]
            ac = a - xp.mean(a, axis=-1, keepdims=True)
            sc = xp.asarray(sc_np, dtype=dt)
            denom = xp.sqrt(xp.sum(ac * ac, axis=-1) * sc_ss) + epsilon
            outs.append(xp.sum(ac * sc, axis=-1) / denom)
        return xp.stack(outs, axis=ax)

    return f


def fourier(b, freq, axis=0, epsilon=0.0):
    """Spectral coherence and phase of every record at one frequency
    index along the value axis ``axis`` (the Thunder ``Series.fourier``
    workload; semantics stated explicitly here since the reference
    mount was empty — SURVEY.md §0).

    Each record is mean-centred and transformed with a real FFT; at bin
    ``freq`` (1 ≤ freq ≤ L//2, DC excluded):

    * **coherence** = ``|co[freq]| / sqrt(sum_{k>=1} |co[k]|^2)`` — the
      fraction of non-DC spectral energy at that bin (1.0 for a pure
      sinusoid at the bin frequency);
    * **phase** = ``angle(co[freq])`` in radians.

    Returns ``(coherence, phase)`` as bolt arrays with the axis removed —
    both still DEFERRED maps (the selection is itself a per-record map,
    so the contract of this module holds and downstream ops fuse).
    ``epsilon`` guards constant records, which otherwise divide 0/0 to
    NaN (same convention as ``zscore``/``crosscorr``).  XLA lowers the
    FFT natively on TPU.
    """
    freq = int(freq)
    ax, split = _value_axis(b, axis)
    length = b.shape[split + ax]
    if not 1 <= freq <= length // 2:
        raise ValueError(
            "freq must be in [1, %d] for an axis of length %d, got %d"
            % (length // 2, length, freq))

    out = _apply_map(b, _fourier_fn(freq, ax, float(epsilon)))
    return (_apply_map(out, _pick_fn(ax, 0)),
            _apply_map(out, _pick_fn(ax, 1)))


@lru_cache(maxsize=128)
def _fourier_fn(freq, ax, epsilon):
    def f(v):
        xp = np if isinstance(v, np.ndarray) else jnp
        dt = xp.promote_types(v.dtype, xp.float32)
        moved = xp.moveaxis(v.astype(dt), ax, -1)
        y = moved - xp.mean(moved, axis=-1, keepdims=True)
        co = xp.fft.rfft(y, axis=-1)
        mag2 = xp.abs(co[..., 1:]) ** 2
        coh = (xp.abs(co[..., freq])
               / (xp.sqrt(xp.sum(mag2, axis=-1)) + epsilon))
        ph = xp.angle(co[..., freq])
        return xp.stack([coh, ph], axis=ax)
    return f


@lru_cache(maxsize=128)
def _pick_fn(ax, i):
    sel = (slice(None),) * ax
    return lambda v: v[sel + (i,)]


def normalize(b, baseline="percentile", perc=20.0, axis=0, epsilon=0.0):
    """Normalise every record to its own baseline along the value axis
    ``axis``: ``(v - base) / denom`` with the sign-aware denominator
    ``denom = base + epsilon`` for ``base >= 0`` and ``base - epsilon``
    otherwise — the ΔF/F transform of the Thunder ``Series.normalize``
    workload, with the guard pushed AWAY from zero so signed baselines
    (e.g. after ``detrend``) cannot land the denominator on it.

    ``baseline``: ``'percentile'`` (the ``perc``-th per-record
    percentile, default 20 — a robust resting level) or ``'mean'``.
    A deferred map on either backend.
    """
    if baseline not in ("percentile", "mean"):
        raise ValueError(
            "baseline must be 'percentile' or 'mean', got %r" % (baseline,))
    perc = float(perc)
    if not 0.0 <= perc <= 100.0:
        raise ValueError("perc must be in [0, 100], got %r" % (perc,))
    ax, _ = _value_axis(b, axis)
    return _apply_map(b, _normalize_fn(baseline, perc, ax, float(epsilon)))


@lru_cache(maxsize=128)
def _normalize_fn(baseline, perc, ax, epsilon):
    def f(v):
        xp = np if isinstance(v, np.ndarray) else jnp
        dt = xp.promote_types(v.dtype, xp.float32)
        vf = v.astype(dt)
        if baseline == "percentile":
            base = xp.percentile(vf, perc, axis=ax, keepdims=True)
        else:
            base = xp.mean(vf, axis=ax, keepdims=True)
        # sign-aware guard: the baseline is SIGNED (e.g. after detrend),
        # so 'base + epsilon' could move a negative baseline ONTO zero;
        # push it away from zero instead (zero itself goes to +epsilon)
        denom = xp.where(base >= 0, base + epsilon, base - epsilon)
        return (vf - base) / denom
    return f
