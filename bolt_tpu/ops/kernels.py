"""Pallas TPU kernels for the hot reduction ops.

The reference's hot loops are Python-per-record inside Spark executors
(``bolt/spark/array.py :: map``/``reduce`` via ``mapValues``/``treeReduce``
— SURVEY §3.2/3.4); XLA already compiles our lowering to fused HBM-bandwidth
code, so these kernels exist for the cases where explicit control wins:

* :func:`fused_map_reduce` — ``sum(fn(x))`` in ONE pass over HBM with an
  on-chip scalar accumulator: the elementwise map, the reduction, and the
  accumulation never round-trip to HBM.
* :func:`fused_stats` — sum / sum-of-squares / min / max in one pass (four
  XLA reductions would read HBM up to four times if fusion declines).

Blocks are carved from the array's ORIGINAL shape — no reshape, because on
TPU a reshape that merges the minor (tiled) dims is a physical relayout
copy, which would double HBM for a 10 GB input.  Grids tile the one or two
leading axes; anything that doesn't tile cleanly falls back to plain jnp.
Off-TPU the kernels run in interpret mode, so the same code paths are
testable on the CPU mesh.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from bolt_tpu.utils import prod

# effective per-block VMEM budget (bytes); conservative vs the ~16 MB/core
# so double buffering and lane padding fit
_VMEM_BUDGET = 6 * 2 ** 20


def _interpret_default():
    return jax.default_backend() not in ("tpu", "axon")


def _padded_bytes(block, itemsize):
    """VMEM footprint of a block after TPU tiling pads the last dim to 128
    lanes and the second-to-last to 8 sublanes."""
    if len(block) == 0:
        return itemsize
    dims = list(block)
    dims[-1] = -(-dims[-1] // 128) * 128
    if len(dims) >= 2:
        dims[-2] = -(-dims[-2] // 8) * 8
    return prod(dims) * itemsize


def _largest_divisor_fitting(n, unit_bytes, budget):
    """Largest divisor d of n with d * unit_bytes <= budget (or None)."""
    best = None
    d = 1
    while d * d <= n:
        if n % d == 0:
            for cand in (d, n // d):
                if cand * unit_bytes <= budget and (best is None or cand > best):
                    best = cand
        d += 1
    return best


def _block_plan(shape, itemsize):
    """Pick ``(grid, block)`` tiling the leading one or two axes of
    ``shape``; None when the array can't be tiled cleanly into VMEM.

    Requires a 128-aligned minor dim: feeding a narrower array to a TPU
    pallas kernel makes XLA relayout-copy the whole operand with padded
    lanes (observed: a 10 GB input became a 21 GB copy) — worse than just
    letting XLA fuse the reduction."""
    if len(shape) == 0:
        return None
    if shape[-1] % 128 != 0:
        return None
    rest1 = _padded_bytes(shape[1:], itemsize) if len(shape) > 1 else itemsize
    t0 = _largest_divisor_fitting(shape[0], rest1, _VMEM_BUDGET)
    if t0 is not None:
        grid = (shape[0] // t0,)
        block = (t0,) + tuple(shape[1:])
        return grid, block
    if len(shape) > 1:
        rest2 = _padded_bytes(shape[2:], itemsize) if len(shape) > 2 else itemsize
        t1 = _largest_divisor_fitting(shape[1], rest2, _VMEM_BUDGET)
        if t1 is not None:
            grid = (shape[0], shape[1] // t1)
            block = (1, t1) + tuple(shape[2:])
            return grid, block
    return None


def _index_map(grid_rank, block):
    if grid_rank == 1:
        return lambda i: (i,) + (0,) * (len(block) - 1)
    return lambda i, j: (i, j) + (0,) * (len(block) - 2)


def _mr_kernel(x_ref, o_ref, *, fn, grid_rank):
    first = pl.program_id(0) == 0
    if grid_rank == 2:
        first = jnp.logical_and(first, pl.program_id(1) == 0)

    @pl.when(first)
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)
    o_ref[...] += jnp.sum(fn(x_ref[...]).astype(o_ref.dtype))


def fused_map_reduce(x, fn=None, interpret=None):
    """``sum(fn(x))`` over all elements, single HBM pass.

    ``fn`` is any traceable elementwise function (identity when ``None``) —
    it runs inside the kernel on VMEM-resident tiles.  Returns a scalar of
    ``x.dtype`` (accumulated in float32 for sub-float32 inputs).
    """
    if fn is None:
        fn = lambda v: v
    plan = _block_plan(x.shape, x.dtype.itemsize)
    # integer inputs fall back: jnp.sum promotes its accumulator, and the
    # kernel's same-dtype accumulation would silently overflow
    if plan is None or not jnp.issubdtype(x.dtype, jnp.floating):
        return jnp.sum(fn(x))
    grid, block = plan
    if interpret is None:
        interpret = _interpret_default()
    acc_dtype = jnp.float32 if x.dtype in (jnp.bfloat16, jnp.float16) else x.dtype

    out = pl.pallas_call(
        partial(_mr_kernel, fn=fn, grid_rank=len(grid)),
        grid=grid,
        in_specs=[pl.BlockSpec(block, _index_map(len(grid), block))],
        out_specs=pl.BlockSpec((1, 1), (lambda i: (0, 0)) if len(grid) == 1
                               else (lambda i, j: (0, 0))),
        out_shape=jax.ShapeDtypeStruct((1, 1), acc_dtype),
        interpret=interpret,
    )(x)
    return out[0, 0].astype(x.dtype)


def _stats_kernel(x_ref, s_ref, sq_ref, mn_ref, mx_ref, *, grid_rank):
    first = pl.program_id(0) == 0
    if grid_rank == 2:
        first = jnp.logical_and(first, pl.program_id(1) == 0)

    @pl.when(first)
    def _init():
        s_ref[...] = jnp.zeros_like(s_ref)
        sq_ref[...] = jnp.zeros_like(sq_ref)
        mn_ref[...] = jnp.full_like(mn_ref, jnp.inf)
        mx_ref[...] = jnp.full_like(mx_ref, -jnp.inf)
    blk = x_ref[...]
    s_ref[...] += jnp.sum(blk)
    sq_ref[...] += jnp.sum(blk * blk)
    mn_ref[...] = jnp.minimum(mn_ref[...], jnp.min(blk))
    mx_ref[...] = jnp.maximum(mx_ref[...], jnp.max(blk))


def fused_stats(x, interpret=None):
    """One-pass ``(sum, sum_sq, min, max)`` over all elements of ``x`` —
    the moment set behind mean/var/std/min/max (the reference computes these
    in one pass too, via StatCounter merges; SURVEY §3.4)."""
    plan = _block_plan(x.shape, x.dtype.itemsize)
    # integer inputs fall back: +/-inf accumulator init and same-dtype
    # sum-of-squares are only correct in floating point
    if plan is None or not jnp.issubdtype(x.dtype, jnp.floating):
        return (jnp.sum(x), jnp.sum(x * x), jnp.min(x), jnp.max(x))
    grid, block = plan
    if interpret is None:
        interpret = _interpret_default()
    dt = jnp.float32 if x.dtype in (jnp.bfloat16, jnp.float16) else x.dtype
    scalar = jax.ShapeDtypeStruct((1, 1), dt)
    out_spec = pl.BlockSpec((1, 1), (lambda i: (0, 0)) if len(grid) == 1
                            else (lambda i, j: (0, 0)))

    s, sq, mn, mx = pl.pallas_call(
        partial(_stats_kernel, grid_rank=len(grid)),
        grid=grid,
        in_specs=[pl.BlockSpec(block, _index_map(len(grid), block))],
        out_specs=[out_spec] * 4,
        out_shape=[scalar] * 4,
        interpret=interpret,
    )(x)
    return (s[0, 0].astype(x.dtype), sq[0, 0].astype(x.dtype),
            mn[0, 0].astype(x.dtype), mx[0, 0].astype(x.dtype))


# svdvals / tallskinny_pca / jacobi_eigh live in bolt_tpu.ops.linalg
