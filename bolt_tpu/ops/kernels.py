"""Pallas TPU kernels for the hot reduction ops.

The reference's hot loops are Python-per-record inside Spark executors
(``bolt/spark/array.py :: map``/``reduce`` via ``mapValues``/``treeReduce``
— SURVEY §3.2/3.4); XLA already compiles our lowering to fused HBM-bandwidth
code, so these kernels exist for the cases where explicit control wins:

* :func:`fused_map_reduce` — ``sum(fn(x))`` in ONE pass over HBM with an
  on-chip scalar accumulator: the elementwise map, the reduction, and the
  accumulation never round-trip to HBM.
* :func:`fused_stats` — sum / sum-of-squares / min / max in one pass (four
  XLA reductions would read HBM up to four times if fusion declines).

Blocks are carved from the array's ORIGINAL shape — no reshape, because on
TPU a reshape that merges the minor (tiled) dims is a physical relayout
copy, which would double HBM for a 10 GB input.  Grids tile the one or two
leading axes; anything that doesn't tile cleanly falls back to plain jnp.
Off-TPU the kernels run in interpret mode, so the same code paths are
testable on the CPU mesh.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from bolt_tpu.utils import prod

# effective per-block VMEM budget (bytes); conservative vs the ~16 MB/core
# so double buffering and lane padding fit
_VMEM_BUDGET = 6 * 2 ** 20


def _interpret_default():
    return jax.default_backend() not in ("tpu", "axon")


def _padded_bytes(block, itemsize):
    """VMEM footprint of a block after TPU tiling pads the last dim to 128
    lanes and the second-to-last to 8 sublanes."""
    if len(block) == 0:
        return itemsize
    dims = list(block)
    dims[-1] = -(-dims[-1] // 128) * 128
    if len(dims) >= 2:
        dims[-2] = -(-dims[-2] // 8) * 8
    return prod(dims) * itemsize


def _largest_divisor_fitting(n, unit_bytes, budget):
    """Largest divisor d of n with d * unit_bytes <= budget (or None)."""
    best = None
    d = 1
    while d * d <= n:
        if n % d == 0:
            for cand in (d, n // d):
                if cand * unit_bytes <= budget and (best is None or cand > best):
                    best = cand
        d += 1
    return best


def _block_plan(shape, itemsize):
    """Pick ``(grid, block)`` tiling the leading one or two axes of
    ``shape``; None when the array can't be tiled cleanly into VMEM.

    Requires a 128-aligned minor dim: feeding a narrower array to a TPU
    pallas kernel makes XLA relayout-copy the whole operand with padded
    lanes (observed: a 10 GB input became a 21 GB copy) — worse than just
    letting XLA fuse the reduction."""
    if len(shape) == 0:
        return None
    if shape[-1] % 128 != 0:
        return None
    rest1 = _padded_bytes(shape[1:], itemsize) if len(shape) > 1 else itemsize
    t0 = _largest_divisor_fitting(shape[0], rest1, _VMEM_BUDGET)
    if t0 is not None:
        grid = (shape[0] // t0,)
        block = (t0,) + tuple(shape[1:])
        return grid, block
    if len(shape) > 1:
        rest2 = _padded_bytes(shape[2:], itemsize) if len(shape) > 2 else itemsize
        t1 = _largest_divisor_fitting(shape[1], rest2, _VMEM_BUDGET)
        if t1 is not None:
            grid = (shape[0], shape[1] // t1)
            block = (1, t1) + tuple(shape[2:])
            return grid, block
    return None


def _index_map(grid_rank, block):
    if grid_rank == 1:
        return lambda i: (i,) + (0,) * (len(block) - 1)
    return lambda i, j: (i, j) + (0,) * (len(block) - 2)


def _mr_kernel(x_ref, o_ref, *, fn, grid_rank):
    first = pl.program_id(0) == 0
    if grid_rank == 2:
        first = jnp.logical_and(first, pl.program_id(1) == 0)

    @pl.when(first)
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)
    o_ref[...] += jnp.sum(fn(x_ref[...]).astype(o_ref.dtype))


def fused_map_reduce(x, fn=None, interpret=None):
    """``sum(fn(x))`` over all elements, single HBM pass.

    ``fn`` is any traceable elementwise function (identity when ``None``) —
    it runs inside the kernel on VMEM-resident tiles.  Returns a scalar of
    ``x.dtype`` (accumulated in float32 for sub-float32 inputs).
    """
    if fn is None:
        fn = lambda v: v
    plan = _block_plan(x.shape, x.dtype.itemsize)
    # integer inputs fall back: jnp.sum promotes its accumulator, and the
    # kernel's same-dtype accumulation would silently overflow
    if plan is None or not jnp.issubdtype(x.dtype, jnp.floating):
        return jnp.sum(fn(x))
    grid, block = plan
    if interpret is None:
        interpret = _interpret_default()
    acc_dtype = jnp.float32 if x.dtype in (jnp.bfloat16, jnp.float16) else x.dtype

    out = pl.pallas_call(
        partial(_mr_kernel, fn=fn, grid_rank=len(grid)),
        grid=grid,
        in_specs=[pl.BlockSpec(block, _index_map(len(grid), block))],
        out_specs=pl.BlockSpec((1, 1), (lambda i: (0, 0)) if len(grid) == 1
                               else (lambda i, j: (0, 0))),
        out_shape=jax.ShapeDtypeStruct((1, 1), acc_dtype),
        interpret=interpret,
    )(x)
    return out[0, 0].astype(x.dtype)


def _stats_kernel(x_ref, s_ref, sq_ref, mn_ref, mx_ref, *, grid_rank):
    first = pl.program_id(0) == 0
    if grid_rank == 2:
        first = jnp.logical_and(first, pl.program_id(1) == 0)

    @pl.when(first)
    def _init():
        s_ref[...] = jnp.zeros_like(s_ref)
        sq_ref[...] = jnp.zeros_like(sq_ref)
        mn_ref[...] = jnp.full_like(mn_ref, jnp.inf)
        mx_ref[...] = jnp.full_like(mx_ref, -jnp.inf)
    blk = x_ref[...]
    s_ref[...] += jnp.sum(blk)
    sq_ref[...] += jnp.sum(blk * blk)
    mn_ref[...] = jnp.minimum(mn_ref[...], jnp.min(blk))
    mx_ref[...] = jnp.maximum(mx_ref[...], jnp.max(blk))


def fused_stats(x, interpret=None):
    """One-pass ``(sum, sum_sq, min, max)`` over all elements of ``x`` —
    the moment set behind mean/var/std/min/max (the reference computes these
    in one pass too, via StatCounter merges; SURVEY §3.4)."""
    plan = _block_plan(x.shape, x.dtype.itemsize)
    # integer inputs fall back: +/-inf accumulator init and same-dtype
    # sum-of-squares are only correct in floating point
    if plan is None or not jnp.issubdtype(x.dtype, jnp.floating):
        return (jnp.sum(x), jnp.sum(x * x), jnp.min(x), jnp.max(x))
    grid, block = plan
    if interpret is None:
        interpret = _interpret_default()
    dt = jnp.float32 if x.dtype in (jnp.bfloat16, jnp.float16) else x.dtype
    scalar = jax.ShapeDtypeStruct((1, 1), dt)
    out_spec = pl.BlockSpec((1, 1), (lambda i: (0, 0)) if len(grid) == 1
                            else (lambda i, j: (0, 0)))

    s, sq, mn, mx = pl.pallas_call(
        partial(_stats_kernel, grid_rank=len(grid)),
        grid=grid,
        in_specs=[pl.BlockSpec(block, _index_map(len(grid), block))],
        out_specs=[out_spec] * 4,
        out_shape=[scalar] * 4,
        interpret=interpret,
    )(x)
    return (s[0, 0].astype(x.dtype), sq[0, 0].astype(x.dtype),
            mn[0, 0].astype(x.dtype), mx[0, 0].astype(x.dtype))


def _welford_kernel(x_ref, mu_ref, m2_ref, mn_ref, mx_ref, *, t0):
    """Chan parallel-combine over leading-axis blocks, elementwise in the
    value shape.  The whole point: the centred second moment needs the
    finished mean, so XLA computes mean/m2 in TWO passes over HBM; here
    each block's two "passes" happen on the VMEM-resident tile and the
    combine is O(value tile), making the welford moments ONE HBM pass."""
    i = pl.program_id(1)
    blk = x_ref[...].astype(mu_ref.dtype)   # sub-f32 inputs widen in VMEM
    bmu = jnp.mean(blk, axis=0)
    bm2 = jnp.sum((blk - bmu[None]) ** 2, axis=0)
    bmn = jnp.min(blk, axis=0)
    bmx = jnp.max(blk, axis=0)

    @pl.when(i == 0)
    def _init():
        mu_ref[...] = bmu
        m2_ref[...] = bm2
        mn_ref[...] = bmn
        mx_ref[...] = bmx

    @pl.when(i > 0)
    def _combine():
        n_a = (i * t0).astype(bmu.dtype)
        n_b = jnp.asarray(t0, bmu.dtype)
        delta = bmu - mu_ref[...]
        tot = n_a + n_b
        mu_ref[...] += delta * (n_b / tot)
        m2_ref[...] += bm2 + delta * delta * (n_a * n_b / tot)
        mn_ref[...] = jnp.minimum(mn_ref[...], bmn)
        mx_ref[...] = jnp.maximum(mx_ref[...], bmx)


def welford_plan(shape, itemsize):
    """Pick ``(t0, v0)`` for :func:`fused_welford` on ``shape`` =
    ``(n, *vshape)``: leading-axis block ``t0`` rows × a value tile that
    splits ``vshape[0]`` into ``v0``-sized pieces.  None when the kernel
    shouldn't engage (non-128-aligned minor dim — feeding one to a TPU
    pallas kernel relayout-copies the whole operand — or nothing tiles
    into VMEM)."""
    if len(shape) < 2 or shape[-1] % 128 != 0 or shape[0] < 2:
        return None
    vshape = shape[1:]
    inner = _padded_bytes(vshape[1:], itemsize) if len(vshape) > 1 else itemsize
    # VMEM holds: input block ×2 (double buffering), a block-sized
    # centred-deviation temporary, and 4 resident accumulator tiles —
    # budget each piece well under the ~16 MB/core limit (an 18.4 MB
    # stack OOM was measured with looser budgets)
    v0 = _largest_divisor_fitting(vshape[0], inner, 256 << 10)
    if v0 is None:
        return None
    tile_bytes = _padded_bytes((v0,) + vshape[1:], itemsize)
    t0 = _largest_divisor_fitting(shape[0], tile_bytes, 2 << 20)
    if t0 is None or t0 < 2:
        return None
    return t0, v0


def fused_welford(x, interpret=None):
    """Single-HBM-pass ``(mean, m2, min, max)`` over axis 0 of ``x``,
    each shaped ``x.shape[1:]`` (``m2`` = sum of squared deviations, the
    StatCounter field).  Returns None when the plan doesn't apply — the
    caller keeps its jnp two-pass path.

    This is the kernel that PAYS ITS RENT (round-2): XLA cannot fuse the
    mean and the centred second moment (sequential dependence → two HBM
    reads), while this kernel reads HBM once — measured 1.52× over the
    fused-XLA two-pass at 10.7 GB on a v5e chip (BASELINE.md).
    """
    plan = welford_plan(x.shape, x.dtype.itemsize)
    if plan is None or not jnp.issubdtype(x.dtype, jnp.floating):
        return None
    t0, v0 = plan
    if interpret is None:
        interpret = _interpret_default()
    n = x.shape[0]
    vshape = x.shape[1:]
    grid = (vshape[0] // v0, n // t0)   # n innermost: accumulators stay put
    block = (t0, v0) + tuple(vshape[1:])
    out_block = (v0,) + tuple(vshape[1:])
    acc = jnp.float32 if x.dtype in (jnp.bfloat16, jnp.float16) else x.dtype
    out_shape = jax.ShapeDtypeStruct(vshape, acc)

    def in_map(j, i):
        return (i, j) + (0,) * (len(vshape) - 1)

    def out_map(j, i):
        return (j,) + (0,) * (len(vshape) - 1)

    mu, m2, mn, mx = pl.pallas_call(
        partial(_welford_kernel, t0=t0),
        grid=grid,
        in_specs=[pl.BlockSpec(block, in_map)],
        out_specs=[pl.BlockSpec(out_block, out_map)] * 4,
        out_shape=[out_shape] * 4,
        interpret=interpret,
    )(x)
    # match the jnp fallback's dtype exactly, so the SAME stats() call
    # returns the same dtype/precision whether or not the kernel engaged
    # (sub-f32 inputs accumulate in f32 in VMEM, then narrow once here)
    return tuple(v.astype(x.dtype) for v in (mu, m2, mn, mx))


def _decode_sum_kernel(q_ref, s_ref, z_ref, o_ref):
    """Affine int8 decode + leading-axis sum, accumulated in f32 on the
    VMEM-resident tile: the quantised block never materialises its
    decoded float form in HBM — decode stays in-register on the way
    into the reduction (the ISSUE-14 compressed-ingest hot path)."""
    i = pl.program_id(1)
    blk = (q_ref[...].astype(jnp.float32) * s_ref[0, 0] + z_ref[0, 0])
    part = jnp.sum(blk, axis=0)

    @pl.when(i == 0)
    def _init():
        o_ref[...] = part

    @pl.when(i > 0)
    def _acc():
        o_ref[...] += part


def fused_decode_sum(q, scale, zp, interpret=None):
    """One-pass decode-and-reduce for an affine-quantised slab: the
    streamed ``sum`` partial ``sum(q * scale + zp, axis=0)`` with the
    int8→f32 decode fused in-register (``q`` is the uint8/int8 wire
    block of shape ``(n, *vshape)``; ``scale``/``zp`` the per-slab
    float sidecar).  The opt-in door for bolt_tpu/tpu/codec.py's int8
    codec (``BOLT_CODEC_KERNEL=1``): XLA already fuses the decode into
    its reduction, so like every kernel here this one exists for the
    geometries where explicit VMEM control wins, returns ``None`` when
    the plan does not engage (the caller keeps the XLA decode path —
    which tests parity-lock this kernel against), and runs in
    interpret mode off-TPU so the same code path is testable on the
    CPU mesh.  The plan is :func:`welford_plan`'s (the blocks widen to
    f32 in VMEM, so the budget uses itemsize 4)."""
    if q.dtype not in (jnp.uint8, jnp.int8) or q.ndim < 2:
        return None
    plan = welford_plan(q.shape, 4)
    if plan is None:
        return None
    t0, v0 = plan
    n = q.shape[0]
    vshape = q.shape[1:]
    grid = (vshape[0] // v0, n // t0)   # n innermost: accumulator stays
    block = (t0, v0) + tuple(vshape[1:])
    out_block = (v0,) + tuple(vshape[1:])
    if interpret is None:
        interpret = _interpret_default()

    def in_map(j, i):
        return (i, j) + (0,) * (len(vshape) - 1)

    def out_map(j, i):
        return (j,) + (0,) * (len(vshape) - 1)

    return pl.pallas_call(
        _decode_sum_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec(block, in_map),
                  pl.BlockSpec((1, 1), lambda j, i: (0, 0)),
                  pl.BlockSpec((1, 1), lambda j, i: (0, 0))],
        out_specs=pl.BlockSpec(out_block, out_map),
        out_shape=jax.ShapeDtypeStruct(vshape, jnp.float32),
        interpret=interpret,
    )(q, jnp.asarray(scale, jnp.float32).reshape(1, 1),
      jnp.asarray(zp, jnp.float32).reshape(1, 1))


# windowing ALONG the minor (lane) axis: the lane-shift chain COMPILES
# up to 13 taps (bisected: 11/13 OK, 15/17 crash the Mosaic subprocess
# — toolchain-specific) but its throughput degrades with width; past 9
# taps the banded-matmul formulation below (round 4) or, for
# non-constant boundary modes, the swap-inland transpose detour serves
# instead, so the DIRECT minor path is capped at the performance
# crossover, not the crash limit
_MINOR_MAX_TAPS = 9


def _band_weights(taps, dtype):
    """The (3·128, 128) channel-mixing weight stack of the banded-matmul
    lane filter: out tile ``t`` = ``[X[t-1]; X[t]; X[t+1]] @ W``.  Row
    block ``kw`` holds the taps that reach from neighbor ``kw-1``."""
    w = len(taps)
    r = w // 2
    wt = np.zeros((3, 128, 128), dtype=np.float64)
    for c in range(128):
        for k in range(w):
            off = c + k - r
            wt[off // 128 + 1, off % 128, c] = taps[k]
    return wt.astype(dtype)


def _band_kernel(x_ref, w_ref, o_ref, *, precision="highest"):
    blk = x_ref[...]                              # (1, S, T, 128)
    zero = jnp.zeros(blk.shape[:-2] + (1, 128), blk.dtype)
    xl = jnp.concatenate([zero, blk[..., :-1, :]], axis=-2)
    xr = jnp.concatenate([blk[..., 1:, :], zero], axis=-2)
    big = jnp.concatenate([xl, blk, xr], axis=-1)  # (1, S, T, 384)
    o_ref[...] = jnp.einsum("bstk,ko->bsto", big, w_ref[...],
                            precision=precision)


# block budget for the band kernel: S·L·itemsize ≤ 2 MB measured safe
# (the kernel holds ~7 block-sized tensors; a 4 MB block crashed the
# Mosaic subprocess with VMEM overflow)
_BAND_BLOCK_BYTES = 2 << 20


def lane_band_pallas(x, taps, interpret=None, precision="highest"):
    """Pallas form of the banded-matmul lane filter: each block reads
    HBM once, builds its 384-channel shifted operand in VMEM, and runs
    ONE MXU matmul — measured 30.5 ms vs the XLA conv form's 40.6 ms on
    a 2.1 GB operand (the round-3 transpose detour: 74 ms).  Returns
    None when the geometry does not fit (caller falls back to
    :func:`lane_band_conv`, then to the transpose detour)."""
    w = len(taps)
    L = x.shape[-1]
    if x.ndim < 2 or L % 128 != 0 or w // 2 > 128 \
            or not jnp.issubdtype(x.dtype, jnp.floating):
        return None
    s1 = x.shape[-2]
    T = L // 128
    S = _largest_divisor_fitting(
        s1, L * x.dtype.itemsize, _BAND_BLOCK_BYTES)
    if S is None:
        return None
    B = prod(x.shape[:-2]) if x.ndim > 2 else 1
    X = x.reshape((B, s1, T, 128))
    if interpret is None:
        interpret = _interpret_default()
    out = pl.pallas_call(
        partial(_band_kernel, precision=precision),
        grid=(B, s1 // S),
        in_specs=[pl.BlockSpec((1, S, T, 128), lambda i, j: (i, j, 0, 0)),
                  pl.BlockSpec((384, 128), lambda i, j: (0, 0))],
        out_specs=pl.BlockSpec((1, S, T, 128), lambda i, j: (i, j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(X.shape, x.dtype),
        interpret=interpret,
    )(X, jnp.asarray(_band_weights(taps, x.dtype).reshape(384, 128)))
    return out.reshape(x.shape)


def lane_band_conv(x, taps, precision="highest"):
    """Wide 1-d correlation ALONG the minor (lane) axis as a banded
    matmul on the MXU (VERDICT r3 next-5 — the round-3 path paid a
    6-pass transpose detour here).

    The lane axis splits into 128-wide tiles ``(..., T, 128)`` — a
    re-tiling XLA performs for free — and the correlation becomes a
    3-tap, 128→128-channel ``conv_general_dilated`` over the tile axis:
    each output tile is ``X[t-1] @ Wl + X[t] @ Wm + X[t+1] @ Wr`` with
    the three (128, 128) bands of the tap matrix as channel-mixing
    weights.  ONE read + ONE write of HBM (the detour pays ~6 passes,
    two of them relayout transposes), with the tap arithmetic moved
    onto the MXU where it is ~free.  Zero-padding of the tile axis IS
    'constant' boundary semantics (the window never reaches past the
    adjacent tile while ``radius <= 128``).  Returns None when the
    geometry does not apply: lane extent not 128-aligned, radius > 128,
    or non-floating dtype."""
    w = len(taps)
    r = w // 2
    L = x.shape[-1]
    if L % 128 != 0 or r > 128 or not jnp.issubdtype(x.dtype, jnp.floating):
        return None
    T = L // 128
    lead = x.shape[:-1]
    rows = prod(lead) if lead else 1
    kernel = jnp.asarray(_band_weights(taps, x.dtype))
    out = jax.lax.conv_general_dilated(
        x.reshape((rows, T, 128)), kernel,
        window_strides=(1,), padding=((1, 1),),
        dimension_numbers=("NWC", "WIO", "NWC"),
        precision=precision)
    return out.reshape(x.shape)


def sepfilter_plan(shape, itemsize, ax, w=1):
    """``(block, grid_axes, grid)`` for :func:`sepfilter1d` on ``shape``
    filtering along ``ax`` with ``w`` taps: blocks keep the FULL ``ax``
    extent (so each block pads and windows itself in VMEM — no
    inter-block halo, no global pad copy) and tile the other axes,
    shrinking greedily left to right (the minor axis in 128-lane units,
    the second-minor in 8s — Mosaic's block rule) until ~1 MB holds the
    block.  ``None`` when nothing fits, the minor dim isn't 128-aligned,
    the grid would exceed TPU's 3 dims, or ``ax`` is the minor axis with
    more than :data:`_MINOR_MAX_TAPS` taps."""
    nd = len(shape)
    if nd == 0 or shape[-1] % 128 != 0:
        return None
    if ax == nd - 1 and w > _MINOR_MAX_TAPS:
        return None
    # ~6 live block-sized tensors (input, padded copy, accumulator,
    # output, double buffering): 1 MB blocks ≈ 6 MB live — measured
    # safe; a 2 MB pad-along-minor block (~13 MB live after lane
    # padding) crashed the Mosaic subprocess with VMEM overflow
    budget = 1 << 20
    block = list(shape)
    for t in [a for a in range(nd) if a != ax]:
        if _padded_bytes(tuple(block), itemsize) <= budget:
            break
        # Mosaic block rule: the last two block dims must be multiples
        # of (8, 128) — or equal to the full array dims
        unit = 128 if t == nd - 1 else (8 if t == nd - 2 else 1)
        if shape[t] % unit != 0:
            continue                      # can't shrink this axis legally
        probe = list(block)
        probe[t] = unit
        unit_bytes = _padded_bytes(tuple(probe), itemsize)
        d = _largest_divisor_fitting(shape[t] // unit, unit_bytes, budget)
        block[t] = d * unit if d else unit
    if _padded_bytes(tuple(block), itemsize) > budget:
        return None
    grid_axes = tuple(a for a in range(nd) if block[a] != shape[a])
    if len(grid_axes) > 3:
        return None
    grid = tuple(shape[a] // block[a] for a in grid_axes) or (1,)
    return tuple(block), grid_axes, grid


def sepfilter_capable(shape, itemsize, ax, w, mode="constant"):
    """True when :func:`sepfilter1d` can serve this geometry and
    boundary ``mode`` — a direct plan, the banded-matmul lane path
    (constant mode only), or the wide-minor-window transpose detour.
    The whole-array fast-path gate in ``overlap._whole_array_sepfilter``
    uses this so it cannot disagree with what the kernel actually
    accepts."""
    if sepfilter_plan(shape, itemsize, ax, w) is not None:
        return True
    nd = len(shape)
    if ax == nd - 1 and w > _MINOR_MAX_TAPS:
        if mode == "constant" and shape[-1] % 128 == 0 and w // 2 <= 128:
            return True                    # banded-matmul lane path
        if nd >= 2 and shape[nd - 2] % 128 == 0:
            swapped = shape[:nd - 2] + (shape[nd - 1], shape[nd - 2])
            return sepfilter_plan(swapped, itemsize, nd - 2, w) is not None
    return False


def _sep1d_kernel(x_ref, o_ref, *, taps, ax, mode):
    # the SAME pad-and-shifted-slice correlation as overlap._filter1d —
    # one algorithm, so the kernel and its chunked/shifted fallback are
    # each other's oracle by construction (import at call time; overlap
    # only imports kernels inside functions, so no cycle)
    from bolt_tpu.ops.overlap import _filter1d
    o_ref[...] = _filter1d(x_ref[...], ax, taps, mode, jnp)


def sepfilter1d(x, taps, ax, mode="constant", interpret=None,
                precision="highest"):
    """1-d correlation of ``x`` with ``taps`` along ``ax`` ('same' size,
    boundary per numpy-pad ``mode``) in ONE HBM pass.

    The XLA shifted-slice formulation re-reads the operand once per tap
    (a 9-tap 2-axis gaussian moved ~25 GB for a 2.1 GB input — measured
    65 ms); here every block is read into VMEM once, pads itself (the
    block holds the full ``ax`` extent, so array-edge semantics are
    exact with no inter-block halo), and the windowed sum runs on
    registers.  Returns ``None`` when the plan doesn't apply (caller
    keeps its shifted-slice path): non-floating dtype, unaligned minor
    dim, or nothing tiles."""
    taps = tuple(float(t) for t in taps)
    if not jnp.issubdtype(x.dtype, jnp.floating):
        return None
    nd = x.ndim
    if ax == nd - 1 and len(taps) > _MINOR_MAX_TAPS:
        if mode == "constant":
            # wide window on the lane axis: banded matmul on the MXU,
            # one read + one write (round 4) — pallas form first, XLA
            # conv form when the block plan doesn't fit
            out = lane_band_pallas(x, taps, interpret=interpret,
                                   precision=precision)
            if out is None:
                out = lane_band_conv(x, taps, precision=precision)
            if out is not None:
                return out
        if nd >= 2 and x.shape[nd - 2] % 128 == 0:
            # non-constant boundary modes (or radius > 128): swap the
            # lane axis inland (both dims stay 128-aligned), window
            # there, swap back — two relayout passes (~4x traffic)
            # still beat a 17x shifted-slice re-read
            y = jnp.swapaxes(x, nd - 2, nd - 1)
            out = sepfilter1d(y, taps, nd - 2, mode=mode,
                              interpret=interpret, precision=precision)
            return None if out is None else jnp.swapaxes(out, nd - 2, nd - 1)
    plan = sepfilter_plan(x.shape, x.dtype.itemsize, ax, len(taps))
    if plan is None:
        return None
    block, grid_axes, grid = plan
    if interpret is None:
        interpret = _interpret_default()
    nd = x.ndim

    def im(*gids):
        pos = [0] * nd
        for g, a in zip(gids, grid_axes):
            pos[a] = g
        return tuple(pos)

    return pl.pallas_call(
        partial(_sep1d_kernel, taps=taps, ax=ax, mode=mode),
        grid=grid,
        in_specs=[pl.BlockSpec(block, im)],
        out_specs=pl.BlockSpec(block, im),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=interpret,
    )(x)


# svdvals / tallskinny_pca / jacobi_eigh live in bolt_tpu.ops.linalg
