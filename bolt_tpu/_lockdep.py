"""Runtime lockdep witness: the package's ONE lock inventory, ranked.

Every ``Lock``/``RLock``/``Condition`` in ``bolt_tpu`` is created
through the factories below with a NAME from :data:`RANKS` — the
declared lock hierarchy (lint rule BLT111 forbids raw ``threading``
lock construction anywhere else, so the inventory below IS the
package's complete set of mutexes).  Ranks order the hierarchy
outermost-first: a thread may only acquire a lock of STRICTLY HIGHER
rank than every lock it already holds (re-entry on the same
RLock/Condition is exempt).  The static half of the contract lives in
``bolt_tpu/analysis/concurrency.py`` (BLT112 checks lexically nested
``with`` blocks against the same table); this module is the dynamic
half — an opt-in witness in the spirit of Linux lockdep:

* **Off by default, one flag check when off** (the obs tracer's
  begin/end discipline): the wrappers delegate straight to the raw
  primitive.  Arm with ``BOLT_LOCKDEP=1`` or :func:`enable`.
* **Armed**: each thread's acquisition stack is tracked; an
  acquisition that violates the rank order is recorded as a violation
  (never raised mid-flight by default — a witness that throws inside
  ``serve``'s worker loop would turn a diagnosis into an outage;
  ``enable(raise_on_violation=True)`` opts into throwing for tests
  that want the traceback at the acquisition site).  The observed
  nesting EDGES are kept for inspection (:func:`edges`) and cycle
  checking (:func:`check`).
* **Dispatch guard**: the engine calls :func:`note_dispatch` at every
  program dispatch; holding any ranked lock across a dispatch — the
  held-lock-across-collective hazard behind the PR 7 deadlock — is a
  violation unless the lock is in :data:`DISPATCH_SAFE`
  (``multistat.group`` holds by design: ``resolve()`` runs the fused
  program under the group lock so a racing ``try_join`` can never
  extend a group mid-dispatch).

Counters land in the obs metrics registry (group ``"lockdep"``) when
the registry is importable; a thread-local busy flag keeps the
witness's own bookkeeping — which takes the registry's (ranked!) lock
— from recursing into itself.

Stdlib-only, importable standalone (``importlib`` path-load) by the
linters: ``scripts/lint_bolt.py --concurrency`` reads :data:`RANKS`
with no jax import.  Modules that are themselves stdlib-only
(``obs/trace.py``, ``obs/metrics.py``, ``_chaos.py``) load this module
by path under the canonical name ``bolt_tpu._lockdep`` so the package
import later adopts the SAME instance (one inventory, one witness
state, however the process started).
"""

import os
import sys
import threading
import traceback

# ---------------------------------------------------------------------
# the declared hierarchy
# ---------------------------------------------------------------------
#
# Rank = nesting depth: LOWER ranks are OUTER locks (taken first, held
# longest), HIGHER ranks are leaves.  The table is the result of
# walking every nested acquisition in the package (PR 17); the
# load-bearing chains it encodes:
#
#   serve.active -> (Server construction: scheduler, arbiter, podwatch
#                    callback subscription, registry gauges)
#   supervisor.state -> podwatch.* -> engine.cache (reform clears it)
#   multistat.group -> engine.cache/order -> obs.registry   (resolve()
#                    dispatches the fused program under the group lock)
#   engine.order -> engine.cache -> obs.trace/obs.registry  (a cold
#                    fallback traces, re-enters get() and counts,
#                    all under the enqueue lock)
#   serve.scheduler / serve.arbiter -> obs.registry         (queue
#                    gauges set under the condition)
#
# obs.registry is the LEAF: every counter increment in the package
# ends there, from under any other lock.
RANKS = {
    # process-wide singleton gates (held across whole-subsystem
    # construction/teardown, so they sit OUTSIDE everything)
    "serve.active": 10,        # serve.py _ACTIVE_LOCK
    "supervisor.active": 12,   # parallel/supervisor.py _ACTIVE_LOCK
    "analysis.strict": 14,     # analysis/__init__.py _ACTIVE_LOCK
    "batched.arm": 16,         # tpu/batched.py _ARM_LOCK
    # fused multi-stat groups hold their lock across the WHOLE
    # resolution — streaming execution, arbiter leases, reseq delivery
    # and the dispatch itself (see DISPATCH_SAFE below) all run under
    # it, so the group lock is an OUTER lock, beneath only the
    # singleton gates (the armed witness proved the first draft of
    # this table wrong: it ranked the group between the stream and
    # engine locks, and every serve-layer fused stat flagged)
    "multistat.group": 18,     # tpu/multistat._StatGroup.lock
    # the pod recovery layer (drives reforms, which reach the engine)
    "supervisor.state": 20,    # supervisor.Supervisor._lock
    "podwatch.watch": 24,      # podwatch._WATCH_LOCK (start/stop gate)
    "podwatch.callbacks": 26,  # podwatch._CB_LOCK
    "podwatch.state": 28,      # podwatch._Watch.lock
    "podwatch.busy": 30,       # podwatch._BUSY_LOCK (collective gate)
    # the serving scheduler and its device-memory arbiter
    "serve.scheduler": 34,     # serve.Server._cond
    "serve.lease": 36,         # serve.ArbiterLease._lock
    "serve.arbiter": 38,       # serve.DeviceArbiter._cond
    # the streaming executor's delivery/accounting locks
    "stream.reseq": 40,        # stream._Reseq._cond
    "stream.uploader_hw": 42,  # stream uploader high-water lock
    # the dispatch engine: enqueue order, per-signature compile
    # coalescing, the executable cache
    "engine.order": 50,        # engine._ORDER_LOCK
    "engine.compile": 52,      # engine._Dispatch._compile_lock
    "engine.cache": 54,        # engine._LOCK
    # leaf caches / utility registries
    "tpu.lru": 60,             # tpu/array.py _LRU_LOCK
    "chaos.registry": 68,      # _chaos.py _LOCK (hit() fires from
    #                            under arbitrary locks; leaf by fiat)
    # observability: EVERY lock's critical section may count/trace
    "obs.trace": 70,           # obs/trace.py _LOCK
    "obs.registry": 72,        # obs/metrics.py Registry._lock (LEAF)
}

# locks that may, BY DESIGN, be held across an engine dispatch.
# multistat.group: _StatGroup.resolve() runs the fused tuple program
# while holding the group lock — the lock is what makes the
# dispatched-group membership immutable; the dispatch inside is a
# single-threaded tail (claimants wait on the group EVENT, not the
# lock).
DISPATCH_SAFE = frozenset({"multistat.group"})

_MAX_VIOLATIONS = 256         # bounded: a hot inversion must not OOM

_ENABLED = os.environ.get("BOLT_LOCKDEP", "").lower() in ("1", "true")
_RAISE = False
_STATE_LOCK = threading.Lock()   # RAW internal lock (guards the
#                                  violation/edge records; deliberately
#                                  outside the inventory — the witness
#                                  cannot witness itself)
_VIOLATIONS = []
_EDGES = set()                   # (outer_name, inner_name) observed
_TLS = threading.local()         # .held: [[wrapper, count], ...]
#                                  .busy: reentrancy guard
_ACQUIRES = [0, 0]               # [tracked acquires, published]: a plain
#                                  GIL-racy tally — counting through the
#                                  registry would serialise EVERY lock
#                                  acquisition in the process on the
#                                  registry lock (measured 6x on the
#                                  concurrent-tenant perf suite); the
#                                  total is flushed to the obs group at
#                                  each dispatch check and on stats()


def _held():
    st = getattr(_TLS, "held", None)
    if st is None:
        st = _TLS.held = []
    return st


_GROUP = None


def _counters():
    """The obs counter group, or ``None`` standalone (the registry
    import must stay lazy: this module is loaded by the jax-free lint
    path, and obs.metrics itself creates its lock through us)."""
    global _GROUP
    if _GROUP is None:
        mod = sys.modules.get("bolt_tpu.obs.metrics")
        if mod is None:
            return None
        try:
            _GROUP = mod.registry().group("lockdep", {
                "acquires": 0,        # tracked acquisitions while armed
                "violations": 0,      # rank inversions + unsafe
                #                       dispatches
                "dispatch_checks": 0,  # note_dispatch() calls armed
            })
        except Exception:
            return None
    return _GROUP


def _count(key, flush_acquires=False):
    if getattr(_TLS, "busy", False):
        return
    grp = _counters()
    if grp is None:
        return
    _TLS.busy = True
    try:
        if flush_acquires:
            delta = _ACQUIRES[0] - _ACQUIRES[1]
            if delta > 0:
                _ACQUIRES[1] += delta
                grp.update(**{key: 1, "acquires": delta})
                return
        grp.add(key)
    finally:
        _TLS.busy = False


def _record(kind, message):
    site = ""
    for fr in reversed(traceback.extract_stack(limit=8)[:-3]):
        if os.sep + "_lockdep" not in fr.filename:
            site = "%s:%d" % (os.path.basename(fr.filename), fr.lineno)
            break
    text = "%s: %s [thread %s, %s]" % (
        kind, message, threading.current_thread().name, site)
    with _STATE_LOCK:
        if len(_VIOLATIONS) < _MAX_VIOLATIONS:
            _VIOLATIONS.append(text)
    _count("violations")
    if _RAISE:
        raise LockOrderError(text)


class LockOrderError(RuntimeError):
    """A lock-hierarchy violation, raised at the acquisition site when
    the witness was armed with ``enable(raise_on_violation=True)``."""


def _note_acquire(wrapper):
    if getattr(_TLS, "busy", False):
        return
    held = _held()
    for ent in held:
        if ent[0] is wrapper:
            if wrapper._reentrant:
                ent[1] += 1
                return
            _record("self-deadlock",
                    "re-acquiring non-reentrant lock %r already held"
                    % wrapper.name)
            break
    _ACQUIRES[0] += 1
    rank = wrapper.rank
    new_edges = []
    for ent in held:
        o = ent[0]
        if o.rank >= rank and o is not wrapper:
            _record("inversion",
                    "acquiring %r (rank %d) while holding %r (rank %d)"
                    " — the declared order is the reverse"
                    % (wrapper.name, rank, o.name, o.rank))
        if o.name != wrapper.name:
            new_edges.append((o.name, wrapper.name))
    if new_edges:
        with _STATE_LOCK:
            _EDGES.update(new_edges)
    held.append([wrapper, 1])


def _note_release(wrapper):
    if getattr(_TLS, "busy", False):
        return
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] is wrapper:
            held[i][1] -= 1
            if held[i][1] <= 0:
                del held[i]
            return
    # release of a lock acquired before arming: not a violation


class _Wrapped:
    """Delegating lock wrapper: raw-primitive speed when the witness is
    off (one module-global flag check), per-thread tracking when armed.
    ``name``/``rank`` are the inventory identity; every instance
    created under the same name shares the rank (per-object instances
    — one lock per ``_Reseq``, per ``_StatGroup`` — are the same
    hierarchy level)."""

    __slots__ = ("name", "rank", "_raw", "_reentrant")

    def __init__(self, name, raw, reentrant):
        if name not in RANKS:
            raise ValueError(
                "lock name %r is not in the declared bolt_tpu lock "
                "inventory (bolt_tpu/_lockdep.RANKS); add it WITH a "
                "rank before using it (lint rule BLT111)" % (name,))
        self.name = name
        self.rank = RANKS[name]
        self._raw = raw
        self._reentrant = reentrant

    def acquire(self, blocking=True, timeout=-1):
        if _ENABLED:
            _note_acquire(self)
        got = self._raw.acquire(blocking, timeout)
        if _ENABLED and not got:
            _note_release(self)
        return got

    def release(self):
        self._raw.release()
        if _ENABLED:
            _note_release(self)

    def locked(self):
        return self._raw.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return "<lockdep %s rank=%d %r>" % (
            "rlock" if self._reentrant else "lock", self.rank, self.name)


class _WrappedCondition(_Wrapped):
    """Condition wrapper: the condition's internal release/reacquire
    inside ``wait`` is invisible to the witness ON PURPOSE — the
    waiting thread acquires nothing while parked, and on wake it holds
    exactly what it held before, so its stack entry stays valid."""

    __slots__ = ()

    def wait(self, timeout=None):
        return self._raw.wait(timeout)

    def wait_for(self, predicate, timeout=None):
        return self._raw.wait_for(predicate, timeout)

    def notify(self, n=1):
        self._raw.notify(n)

    def notify_all(self):
        self._raw.notify_all()


def lock(name):
    """A named ``threading.Lock`` from the declared inventory."""
    return _Wrapped(name, threading.Lock(), reentrant=False)


def rlock(name):
    """A named ``threading.RLock`` from the declared inventory."""
    return _Wrapped(name, threading.RLock(), reentrant=True)


def condition(name):
    """A named ``threading.Condition`` (own RLock) from the declared
    inventory."""
    return _WrappedCondition(name, threading.Condition(), reentrant=True)


# ---------------------------------------------------------------------
# arming / inspection
# ---------------------------------------------------------------------

def enable(raise_on_violation=False):
    """Arm the witness (process-wide).  Violations are RECORDED by
    default; ``raise_on_violation=True`` additionally raises
    :class:`LockOrderError` at the offending acquisition (test mode —
    the traceback lands at the real site)."""
    global _ENABLED, _RAISE
    _RAISE = bool(raise_on_violation)
    _ENABLED = True


def disable():
    """Disarm the witness (records are kept until :func:`reset`)."""
    global _ENABLED, _RAISE
    _ENABLED = False
    _RAISE = False


def enabled():
    return _ENABLED


def reset():
    """Clear recorded violations and observed edges."""
    with _STATE_LOCK:
        del _VIOLATIONS[:]
        _EDGES.clear()


def violations():
    """Snapshot list of recorded violation strings."""
    with _STATE_LOCK:
        return list(_VIOLATIONS)


def stats():
    """Witness tallies ``{acquires, violations}`` (process lifetime).
    Also flushes the acquire tally into the obs ``lockdep`` counter
    group when the registry is importable."""
    grp = _counters()
    if grp is not None and not getattr(_TLS, "busy", False):
        _TLS.busy = True
        try:
            delta = _ACQUIRES[0] - _ACQUIRES[1]
            if delta > 0:
                _ACQUIRES[1] += delta
                grp.update(acquires=delta)
        finally:
            _TLS.busy = False
    with _STATE_LOCK:
        n_viol = len(_VIOLATIONS)
    return {"acquires": _ACQUIRES[0], "violations": n_viol}


def edges():
    """Sorted observed nesting edges ``(outer_name, inner_name)``."""
    with _STATE_LOCK:
        return sorted(_EDGES)


def held_names():
    """Names the CALLING thread currently holds (outer first)."""
    return [ent[0].name for ent in _held()]


def check():
    """Cycles in the observed edge graph (each as a name list).  With
    every lock ranked a cycle implies a recorded inversion too; this is
    the belt-and-braces view tests assert empty."""
    with _STATE_LOCK:
        graph = {}
        for a, b in _EDGES:
            graph.setdefault(a, set()).add(b)
    cycles, done = [], set()

    def dfs(node, stack, on_stack):
        done.add(node)
        on_stack.add(node)
        stack.append(node)
        for nxt in graph.get(node, ()):
            if nxt in on_stack:
                cycles.append(stack[stack.index(nxt):] + [nxt])
            elif nxt not in done:
                dfs(nxt, stack, on_stack)
        stack.pop()
        on_stack.discard(node)

    for node in sorted(graph):
        if node not in done:
            dfs(node, [], set())
    return cycles


def note_dispatch(what="engine.dispatch"):
    """Engine seam: called at every program dispatch.  Holding a ranked
    lock here (outside :data:`DISPATCH_SAFE`) is the
    held-lock-across-collective hazard — another thread blocked on that
    lock can never reach its own enqueue, and a cross-device rendezvous
    wedges exactly like the pre-order-lock PR 7 deadlock."""
    if not _ENABLED:
        return
    _count("dispatch_checks", flush_acquires=True)
    for ent in _held():
        name = ent[0].name
        if name not in DISPATCH_SAFE:
            _record("dispatch-under-lock",
                    "%s while holding %r (rank %d); dispatching under "
                    "a lock stalls every thread contending it for a "
                    "full device round-trip — release before "
                    "dispatching, or add the lock to DISPATCH_SAFE "
                    "with a written justification"
                    % (what, name, ent[0].rank))
