"""Factory / mode dispatch: the single construction entry point.

Reference: ``bolt/factory.py`` — ``array/ones/zeros/concatenate`` over a
constructor registry ``[('local', ConstructLocal), ('spark',
ConstructSpark)]`` with dispatch on an execution context in the arguments
(symbol-level citation, SURVEY.md §0).  Here the registry is ``[('tpu',
ConstructTPU), ('local', ConstructLocal)]`` and the context that selects the
distributed backend is a ``jax.sharding.Mesh`` instead of a SparkContext.
"""

from bolt_tpu.local.construct import ConstructLocal
from bolt_tpu.tpu.construct import ConstructTPU

# checked in order; the local backend is the fallback
constructors = [("tpu", ConstructTPU), ("local", ConstructLocal)]


def _lookup(*args, **kwargs):
    """Find the constructor class for the given arguments (reference:
    ``bolt/factory.py`` dispatch helper)."""
    mode = kwargs.get("mode")
    if mode is not None:
        for name, cls in constructors:
            if name == mode:
                return cls
        raise ValueError("unknown mode %r (known: %s)"
                         % (mode, [n for n, _ in constructors]))
    for name, cls in constructors:
        if cls._argcheck(*args, **kwargs):
            return cls
    return ConstructLocal


def array(a, context=None, axis=(0,), mode=None, dtype=None, npartitions=None):
    """Create a bolt array from an array-like.

    ``mode='tpu'`` (or passing a ``Mesh`` as ``context``) distributes
    ``axis`` as key axes over the mesh; otherwise a local NumPy-backed array
    is returned (reference: ``bolt/factory.py :: array``).
    """
    cls = _lookup(context=context, mode=mode)
    if cls is ConstructLocal:
        return ConstructLocal.array(a, dtype=dtype)
    return ConstructTPU.array(a, context=context, axis=axis, dtype=dtype,
                              npartitions=npartitions)


def ones(shape, context=None, axis=(0,), mode=None, dtype=None):
    """Bolt array of ones (reference: ``bolt/factory.py :: ones``)."""
    cls = _lookup(context=context, mode=mode)
    if cls is ConstructLocal:
        return ConstructLocal.ones(shape, dtype=dtype)
    return ConstructTPU.ones(shape, context=context, axis=axis, dtype=dtype)


def zeros(shape, context=None, axis=(0,), mode=None, dtype=None):
    """Bolt array of zeros (reference: ``bolt/factory.py :: zeros``)."""
    cls = _lookup(context=context, mode=mode)
    if cls is ConstructLocal:
        return ConstructLocal.zeros(shape, dtype=dtype)
    return ConstructTPU.zeros(shape, context=context, axis=axis, dtype=dtype)


def full(shape, value, context=None, axis=(0,), mode=None, dtype=None):
    """Bolt array filled with ``value`` (numpy ``full`` semantics: the
    dtype defaults to the fill value's, so ``full(s, 2)`` is integral and
    ``full(s, 2.0)`` floating; extension beyond the reference factory).
    ``mode='tpu'`` builds each shard on its own device, like
    ``ones``/``zeros``."""
    cls = _lookup(context=context, mode=mode)
    if cls is ConstructLocal:
        return ConstructLocal.full(shape, value, dtype=dtype)
    return ConstructTPU.full(shape, value, context=context, axis=axis,
                             dtype=dtype)


def randn(shape, context=None, axis=(0,), mode=None, dtype=None, seed=0):
    """Bolt array of standard normals (extension beyond the reference
    factory).  ``mode='tpu'`` generates each shard on its own device — no
    host materialisation; backends use different RNG streams."""
    cls = _lookup(context=context, mode=mode)
    if cls is ConstructLocal:
        return ConstructLocal.randn(shape, dtype=dtype, seed=seed)
    return ConstructTPU.randn(shape, context=context, axis=axis, dtype=dtype,
                              seed=seed)


def rand(shape, context=None, axis=(0,), mode=None, dtype=None, seed=0):
    """Bolt array of uniform [0, 1) samples (extension beyond the reference
    factory); see :func:`randn`."""
    cls = _lookup(context=context, mode=mode)
    if cls is ConstructLocal:
        return ConstructLocal.rand(shape, dtype=dtype, seed=seed)
    return ConstructTPU.rand(shape, context=context, axis=axis, dtype=dtype,
                             seed=seed)


def fromcallback(fn, shape, context=None, axis=(0,), mode=None, dtype=None,
                 chunks=None, checkpoint=None, per_process=False,
                 codec=None):
    """Build a bolt array by calling ``fn(index_slices) -> block`` per
    index range — the sharded data-loader (extension beyond the reference
    factory, whose ``sc.parallelize`` scatter needs the full array at the
    driver).  ``mode='tpu'`` with an explicit ``dtype``: a LAZY streaming
    source — reduction terminals stream it slab-by-slab through the
    out-of-core executor (``bolt_tpu.stream``), other consumers
    materialise one call per device shard; ``chunks`` sets records per
    streamed slab; ``checkpoint=dir`` makes every streamed run over the
    source RESUMABLE (slab-level fold checkpoints — see
    ``stream.resumable``); ``per_process=True`` opts a MULTI-PROCESS
    mesh into the pod-scale streaming contract (each host's loader is
    invoked only for its own shard of each slab; the cross-host fold
    runs as mesh collectives — ``bolt_tpu.parallel.multihost``);
    ``codec=`` names an ingest codec (``bolt_tpu.tpu.codec``) so
    streamed slabs ship ENCODED and decode on device — fewer
    host→device bytes on the transfer-bound path.
    Local mode: one call for the whole array."""
    cls = _lookup(context=context, mode=mode)
    if cls is ConstructLocal:
        return ConstructLocal.fromcallback(fn, shape, axis=axis, dtype=dtype)
    return ConstructTPU.fromcallback(fn, shape, context=context, axis=axis,
                                     dtype=dtype, chunks=chunks,
                                     checkpoint=checkpoint,
                                     per_process=per_process, codec=codec)


def fromiter(blocks, shape, context=None, axis=(0,), mode=None, dtype=None,
             checkpoint=None, codec=None):
    """Build a bolt array from an ITERABLE of consecutive record blocks
    (key-axes-first layout along the first key axis) — the sequential
    streaming constructor for sources without random access.  ``dtype``
    is required.  ``mode='tpu'``: a lazy streaming source like
    :func:`fromcallback` (``checkpoint=dir`` arms slab-level resume —
    meaningful only for RE-ITERABLE block sources; a one-shot generator
    dies with the process, which ``analysis.check`` flags as BLT011;
    ``codec=`` arms codec-encoded ingest like :func:`fromcallback`'s);
    local mode assembles the blocks on host."""
    cls = _lookup(context=context, mode=mode)
    if cls is ConstructLocal:
        return ConstructLocal.fromiter(blocks, shape, axis=axis, dtype=dtype)
    return ConstructTPU.fromiter(blocks, shape, context=context, axis=axis,
                                 dtype=dtype, checkpoint=checkpoint,
                                 codec=codec)


def concatenate(arrays, axis=0, context=None, mode=None):
    """Concatenate bolt arrays (reference: ``bolt/factory.py ::
    concatenate``).  Dispatches on the first array's backend unless
    overridden."""
    if isinstance(arrays, (tuple, list)) and len(arrays) and mode is None \
            and context is None:
        from bolt_tpu.tpu.array import BoltArrayTPU
        if isinstance(arrays[0], BoltArrayTPU):
            return ConstructTPU.concatenate(arrays, axis=axis)
        return ConstructLocal.concatenate(arrays, axis=axis)
    cls = _lookup(context=context, mode=mode)
    if cls is ConstructLocal:
        return ConstructLocal.concatenate(arrays, axis=axis)
    return ConstructTPU.concatenate(arrays, axis=axis, context=context)
