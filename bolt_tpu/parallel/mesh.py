"""Device-mesh construction and multi-host initialisation.

This module occupies the structural slot of the reference's execution-engine
context: where Bolt hands a ``SparkContext`` to its constructors, the TPU
backend hands a ``jax.sharding.Mesh`` (reference call sites:
``bolt/spark/construct.py :: ConstructSpark.array`` takes ``context``;
see SURVEY.md §2.5 for the Spark-shuffle → ICI/DCN collective mapping).

Multi-host usage keeps the single-controller programming model: after
:func:`initialize_distributed`, a mesh built from ``jax.devices()`` spans all
hosts and every collective rides ICI within a slice and DCN across slices,
inserted by XLA from the sharding specs — the mesh IS the cluster.
"""

import numpy as np

import jax


def default_mesh(devices=None, axis_name="k"):
    """A 1-d mesh over all available devices.

    Every ``context=None`` TPU construction lands here, so single-chip and
    CPU-test runs work without ceremony.
    """
    if devices is None:
        devices = jax.devices()
    return jax.sharding.Mesh(np.asarray(devices), (axis_name,))


def make_mesh(shape, axis_names, devices=None):
    """An n-d mesh with named axes, e.g. ``make_mesh((4, 2), ('k', 'v'))``.

    Thin wrapper over ``jax.make_mesh`` so callers never import jax
    internals; ``jax.make_mesh`` picks a device order that favours ICI
    nearest-neighbour topology.  Axes are Auto-typed: this framework drives
    sharding through constraints and lets GSPMD propagate.
    """
    if devices is not None:
        return jax.sharding.Mesh(
            np.asarray(devices).reshape(shape), tuple(axis_names))
    from bolt_tpu._compat import make_mesh as _make_mesh
    return _make_mesh(shape, axis_names)


def ensure_auto(mesh):
    """Return an Auto-axis-typed twin of ``mesh``.

    ``jax.make_mesh`` defaults to Explicit axis types in recent JAX; this
    framework's lowering uses ``with_sharding_constraint`` + GSPMD
    propagation, which requires Auto axes, so user-supplied meshes are
    normalised on entry (identity on runtimes without typed mesh axes)."""
    from bolt_tpu._compat import ensure_auto_mesh
    return ensure_auto_mesh(mesh)


def initialize_distributed(coordinator_address=None, num_processes=None,
                           process_id=None):
    """Initialise multi-host JAX (DCN).  No-op when already initialised or
    running single-process.

    Replaces the reference's reliance on the Spark cluster manager for
    multi-node bring-up (SURVEY.md §2.5).  Thin alias of
    :func:`bolt_tpu.parallel.multihost.initialize` — the bootstrap (and
    every other ``jax.distributed`` / process-topology touch, lint rule
    BLT110) lives there.
    """
    from bolt_tpu.parallel import multihost
    multihost.initialize(coordinator_address=coordinator_address,
                         num_processes=num_processes,
                         process_id=process_id)
