"""Explicit halo exchange for shard_map programs.

The reference's chunk ``padding`` ships overlapping blocks through the
Spark shuffle (``bolt/spark/chunk.py :: ChunkedArray._chunk`` with
``padding`` — SURVEY §2.4 maps it to ``lax.ppermute`` neighbour exchange).
When a value axis is sharded across the mesh, each shard needs its
neighbours' edge slices before windowed/stencil compute; this module is the
ppermute lowering of that exchange, for users writing explicit
``shard_map`` kernels.  (The implicit path — slicing a padded window out of
a global sharded array under jit — is handled by GSPMD automatically; this
is the explicit-collective counterpart, like ``tpu/stats.py`` is for
``rdd.aggregate``.)
"""

import jax
import jax.numpy as jnp


def exchange_halo(local, pad, axis, axis_name, mode="zero"):
    """Inside ``shard_map``: extend ``local`` along ``axis`` with ``pad``
    elements fetched from the previous/next shard on mesh axis
    ``axis_name`` via ``lax.ppermute``.

    ``mode='zero'`` fills the outer boundary of the first/last shard with
    zeros (callers that clip — the reference's semantics — can trim or mask
    using ``jax.lax.axis_index``); ``mode='wrap'`` exchanges cyclically.

    Returns an array whose ``axis`` is ``2*pad`` longer than ``local``'s.
    """
    if pad <= 0:
        return local
    if pad > local.shape[axis]:
        # a halo wider than the shard would need data from beyond the
        # immediate neighbour; slice() would silently shrink instead
        raise ValueError(
            "halo pad %d exceeds the per-shard extent %d on axis %d"
            % (pad, local.shape[axis], axis))
    from bolt_tpu._compat import axis_size
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)

    def take(arr, sl):
        slicer = [slice(None)] * arr.ndim
        slicer[axis] = sl
        return arr[tuple(slicer)]

    # my right edge goes to my right neighbour (becomes their left halo)
    right_edge = take(local, slice(local.shape[axis] - pad, None))
    left_halo = jax.lax.ppermute(
        right_edge, axis_name, [(i, (i + 1) % n) for i in range(n)])
    # my left edge goes to my left neighbour (becomes their right halo)
    left_edge = take(local, slice(0, pad))
    right_halo = jax.lax.ppermute(
        left_edge, axis_name, [(i, (i - 1) % n) for i in range(n)])

    if mode == "zero":
        def bcast(cond):
            shape = [1] * local.ndim
            return jnp.asarray(cond).reshape(shape)
        left_halo = jnp.where(bcast(idx == 0),
                              jnp.zeros_like(left_halo), left_halo)
        right_halo = jnp.where(bcast(idx == n - 1),
                               jnp.zeros_like(right_halo), right_halo)
    elif mode != "wrap":
        raise ValueError("mode must be 'zero' or 'wrap', got %r" % (mode,))

    return jnp.concatenate([left_halo, local, right_halo], axis=axis)
