from bolt_tpu.parallel.mesh import (default_mesh, ensure_auto,
                                    initialize_distributed, make_mesh)
from bolt_tpu.parallel.sharding import key_sharding, reshard

__all__ = ["default_mesh", "ensure_auto", "make_mesh",
           "initialize_distributed", "key_sharding", "reshard"]
