from bolt_tpu.parallel import multihost
from bolt_tpu.parallel.halo import exchange_halo
from bolt_tpu.parallel.mesh import (default_mesh, ensure_auto,
                                    initialize_distributed, make_mesh)
from bolt_tpu.parallel.sharding import (combined_spec, key_sharding,
                                        key_spec, reshard, spec_names)

__all__ = ["default_mesh", "ensure_auto", "make_mesh", "multihost",
           "initialize_distributed", "combined_spec", "key_spec", "spec_names",
           "key_sharding", "reshard", "exchange_halo"]
