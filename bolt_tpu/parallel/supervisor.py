"""Self-healing pods: the recovery supervisor (ISSUE 12).

PR 11 made pod failure *survivable* — ``kill -9`` of one member becomes
a pointed :class:`podwatch.PeerLostError` on every survivor and
``multihost.reform`` shrinks the runtime onto them — but recovery was
still *manual*: the caller had to catch the error, agree a fresh
coordinator out of band, and call ``reform`` by hand, and the pod could
only ever shrink.  This module closes the loop from "failure is
detectable" to "failure is self-healing" — the detect → drain → reform
→ resume → re-expand contract Spark's driver runs for its executors
(SURVEY §3.3), here run peer-to-peer because a Bolt pod has no driver:

* **auto-recovery** — a :class:`Supervisor` on every member subscribes
  ``podwatch.on_peer_death``; on a loss the survivors each elect the
  SAME coordinator deterministically (lowest surviving rank), the
  coordinator allocates a fresh port and publishes the reform **plan**
  (address, ordered member list, next transport epoch) through the
  heartbeat transport (``plan_set``/``plan_get`` — no out-of-band
  agreement anywhere), and every survivor drives
  ``multihost.reform`` from the plan.  Retries ride a bounded
  exponential backoff (``BOLT_SUPERVISE_RETRIES`` /
  ``BOLT_SUPERVISE_BACKOFF``); a SECOND failure landing mid-reform
  just fails that attempt and the loop re-enters on the new survivor
  set (a liveness re-probe on the plan's epoch re-reads who is
  actually alive);
* **automatic re-expansion** — a restarted or replacement process
  rings the transport's REJOIN door (:func:`attach` →
  ``podwatch.rejoin``).  Incumbent supervisors request a QUIESCE: any
  in-flight pod stream stops at its next slab-boundary checkpoint
  (``podwatch.quiesce_gate`` — a single-writer decision fenced by the
  checkpoint barrier, so every process abandons the same watermark),
  and once the process is idle the pod reforms UP to the larger
  topology.  Pod fold partials are psum-replicated, so the same
  topology-remap resume that makes shrink bit-exact makes growth
  bit-exact;
* **quarantine** — a peer that keeps flapping (dies, rejoins, dies
  again: ``BOLT_SUPERVISE_QUARANTINE`` strikes, default 2) latches
  into a quarantine list; its rejoin announcements are ignored, so it
  cannot thrash the pod through endless reform cycles.

The serving layer rides this as ``serve.Server(supervise=True)``: peer
death drains admission (as before), the supervisor reforms
automatically, held ``retries=`` re-attempts resume from the
checkpoint — ZERO caller intervention — and the arbiter budget is
rescaled to the surviving capacity share (BLT010 floors recompute
against it).  Observability: registry group ``supervisor``
(``reforms``/``rejoins``/``peer_losses``/``backoffs``/``giveups``/
``quarantined``/``supervise_seconds``), spans ``supervisor.reform``,
instants ``supervisor.rejoin``/``supervisor.backoff``.

Practical transport note: the plan/rejoin channel needs a rendezvous
medium that OUTLIVES the dead peer.  The shared-dir transport
(``BOLT_POD_HB_DIR``) always qualifies; the ``jax.distributed`` KV
store lives on the original coordinator, so KV-backed supervision
recovers from non-coordinator losses only — the constructor does not
refuse, the recovery loop degrades loudly when the store is gone.

Deterministic fault points: ``supervisor.elect`` (top of every
recovery attempt) and ``supervisor.rejoin`` (the rejoin-door handler)
— ``bolt_tpu._chaos`` seams, so double-failure-during-reform and
rejoin-storm interleavings replay exactly in tests.

Lint: a blessed home of raw thread construction would be wrong here —
the one background thread is created through the stdlib ``threading``
module inside this file, which BLT108 exempts alongside
``podwatch.py`` (the recovery driver IS pod-lifecycle plumbing).
"""

import json
import os
import socket
import threading
import time

from bolt_tpu import _chaos
from bolt_tpu import _lockdep
from bolt_tpu.obs import metrics as _metrics
from bolt_tpu.obs import trace as _obs
from bolt_tpu.obs.trace import clock as _clock
from bolt_tpu.parallel import podwatch as _podwatch

# ---------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------

# bounded exponential backoff for the recovery loop: attempt k sleeps
# backoff * 2^k seconds before re-electing (a second failure mid-reform
# re-enters here; the budget keeps a permanently sick pod from spinning)
_DEF_RETRIES = max(0, int(os.environ.get("BOLT_SUPERVISE_RETRIES", "3")))
_DEF_BACKOFF = float(os.environ.get("BOLT_SUPERVISE_BACKOFF", "0.5"))

# strikes before a flapping peer is quarantined (each recovery a peer's
# death triggers is one strike; a quarantined identity's rejoin
# announcements are ignored)
_DEF_QUARANTINE = max(1, int(os.environ.get("BOLT_SUPERVISE_QUARANTINE",
                                            "2")))

# growth-recovery quiesce drain budget (seconds): how long to wait for
# in-flight pod streams to reach a slab-boundary checkpoint before the
# growth is DEFERRED (0 = the default max(60, 10x watchdog deadline))
_DEF_DRAIN = float(os.environ.get("BOLT_SUPERVISE_DRAIN", "0"))

# the host part of a published coordinator address: every member must
# be able to reach the elected coordinator here.  Localhost clusters
# (the test harness) use the default; a real pod sets the coordinator
# host its DNS/overlay resolves.
_DEF_HOST = os.environ.get("BOLT_SUPERVISE_HOST", "127.0.0.1")

_SCHEMA = {
    "peer_losses": 0,         # deaths observed (recovery triggers)
    "reforms": 0,             # successful reform drives (down or up)
    "rejoins": 0,             # identities folded back in by reform-up
    "backoffs": 0,            # failed attempts slept through
    "giveups": 0,             # recoveries abandoned (budget exhausted)
    "quarantined": 0,         # rejoin announcements ignored
    "supervise_seconds": 0.0,  # pause -> resume wall, totalled
}


class SuperviseError(RuntimeError):
    """The supervisor abandoned a recovery: the retry budget is
    exhausted (every attempt's failure chained below), or the
    transport cannot carry a plan (KV store died with the
    coordinator).  The pod is still drained — manual
    ``multihost.reform`` remains possible."""


def free_port(host="127.0.0.1"):
    """One OS-allocated free port (the elected coordinator binds the
    reform service here; the plan publishes it)."""
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def _fastfail_init_timeout():
    """The default reform bring-up window when the caller set none: a
    member that died between the plan and the bring-up must fail the
    attempt in SECONDS (so the loop re-enters on the new survivor
    set), not jax's default 120 s init window.  Scaled off the
    liveness deadline when a watch is running; a healthy localhost
    bring-up completes in well under a second."""
    return max(15.0, 5 * (_podwatch.deadline() or 2.0))


# ---------------------------------------------------------------------
# the supervisor
# ---------------------------------------------------------------------

_ACTIVE = None
_ACTIVE_LOCK = _lockdep.lock("supervisor.active")


class Supervisor:
    """One pod member's recovery controller.  Construct it on every
    member (``serve.Server(supervise=True)`` does); it idles until the
    liveness watch reports a death or a rejoin, then drives the full
    recovery autonomously.  ``on_pause(reason)`` / ``on_resume(info)``
    hooks let a scheduler drain and resume admission around the
    reform (``info`` carries ``{"nproc", "rejoined", "reason"}``).

    Thread model: callbacks arrive from the watch thread and only
    enqueue; ONE supervisor thread runs recoveries, so two events
    cannot race two reforms."""

    def __init__(self, retries=None, backoff=None, host=None,
                 quarantine_after=None, on_pause=None, on_resume=None,
                 init_timeout=None, ident_map=None, gen=0, joined=None):
        self.retries = _DEF_RETRIES if retries is None else max(
            0, int(retries))
        self.backoff = _DEF_BACKOFF if backoff is None else float(backoff)
        self.host = host or _DEF_HOST
        self.quarantine_after = (_DEF_QUARANTINE if quarantine_after
                                 is None else max(1, int(quarantine_after)))
        self.on_pause = on_pause
        self.on_resume = on_resume
        # a reform bring-up waits for EVERY member to connect; a member
        # that died mid-reform must fail the attempt in seconds, not
        # jax's default 120s init window
        self.init_timeout = init_timeout
        self.failed = None             # the giveup error, if any
        self._lock = _lockdep.lock("supervisor.state")
        # last plan generation DRIVEN by this member — the follower
        # adoption floor is _gen + 1, so attach() must seed it with
        # the plan it joined by or a retained stale generation on the
        # transport could be re-adopted on this member's next recovery
        self._gen = int(gen)
        self._strikes = {}             # identity -> recovery triggers
        self._quarantine = set()
        self._pending_deaths = set()
        self._pending_rejoins = set()
        self._tried_gens = set()
        # rank -> PERSISTENT identity.  Ranks are remapped on every
        # reform, so strikes/quarantine keyed by rank would
        # misattribute a rejoiner's flapping to whichever incumbent
        # inherits its old rank; deaths strike the identity instead.
        # Unmapped ranks default to the birth identity "i<rank>";
        # attach() seeds the rejoiner's map from the plan it joined by.
        self._ident_by_rank = dict(ident_map or {})
        self._joined = set(joined or ())  # idents already folded in
        self._last = {}                # last recovery's timing
        self._recovered = threading.Event()
        self._recovered.set()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._probe = None             # (nproc, pid, dir, interval,
        #                                timeout) of the last live watch
        #                                — the liveness re-probe after a
        #                                failed reform attempt
        self._counters = _metrics.registry().group("supervisor", _SCHEMA)
        self._handles = (
            _podwatch.on_peer_death(self._on_death),
            _podwatch.on_rejoin(self._on_rejoin),
        )
        self._thread = threading.Thread(
            target=self._run, name="bolt-supervisor", daemon=True)
        self._thread.start()
        global _ACTIVE
        with _ACTIVE_LOCK:
            _ACTIVE = self

    # -- event intake (watch thread) -----------------------------------

    def _ident_of(self, pid):
        """The persistent identity currently holding rank ``pid``."""
        return self._ident_by_rank.get(int(pid), "i%d" % int(pid))

    def _on_death(self, pid):
        with self._lock:
            pid = int(pid)
            # one DEATH = one strike, not one liveness latch: the
            # re-probe after a failed reform attempt starts a fresh
            # watch where the same dead peer re-latches and fires this
            # callback again — without the dedupe a peer that died
            # exactly once would hit the default 2-strike quarantine
            # after one transient reform failure
            relatch = pid in self._pending_deaths
            self._pending_deaths.add(pid)
            ident = self._ident_of(pid)
            if not relatch:
                self._strikes[ident] = self._strikes.get(ident, 0) + 1
            # latch at the threshold strike IMMEDIATELY: the flapper's
            # very next rejoin is ignored — latching only at reform
            # success would re-admit it for one more full
            # quiesce/reform-up/shrink cycle first
            if self._strikes[ident] >= self.quarantine_after:
                self._quarantine.add(ident)
            # a dead member is no longer joined: its NEXT rejoin
            # announcement must ring through (not be dropped as
            # marker-sweep lag), or a restarted member could never
            # come back
            self._joined.discard(ident)
        if not relatch:
            self._counters.add("peer_losses")
        self._recovered.clear()
        self._wake.set()

    def _on_rejoin(self, ident):
        _chaos.hit("supervisor.rejoin")
        with self._lock:
            if ident in self._quarantine:
                self._counters.add("quarantined")
                _obs.event("supervisor.quarantined", ident=ident)
                return
            if ident in self._joined:
                return                # already a member (marker sweep lag)
            self._pending_rejoins.add(ident)
        _obs.event("supervisor.rejoin", ident=ident)
        self._recovered.clear()
        self._wake.set()

    # -- queries --------------------------------------------------------

    def quarantined(self):
        with self._lock:
            return sorted(self._quarantine)

    def stats(self):
        out = dict(self._counters.snapshot())
        with self._lock:
            out["quarantine"] = sorted(self._quarantine)
            out["generation"] = self._gen
            out["pending_deaths"] = sorted(self._pending_deaths)
            out["pending_rejoins"] = sorted(self._pending_rejoins)
            out.update(self._last)     # last_reform_seconds /
            #                            last_recovery_seconds
        out["failed"] = str(self.failed) if self.failed else None
        return out

    def config(self):
        """The supervised recovery contract ``explain()`` renders."""
        return {"retries": self.retries, "backoff": self.backoff,
                "quarantine_after": self.quarantine_after,
                "quarantine": self.quarantined(),
                "host": self.host}

    def wait_recovered(self, timeout=None):
        """Block until no recovery is pending (True), or ``timeout``
        elapses (False).  Raises the giveup error if the last recovery
        was abandoned."""
        ok = self._recovered.wait(timeout)
        if self.failed is not None:
            raise self.failed
        return ok

    # -- lifecycle ------------------------------------------------------

    def close(self):
        """Stop the supervisor: deregister the watch callbacks and
        join the recovery thread.  Does not touch the pod."""
        self._stop.set()
        self._wake.set()
        for h in self._handles:
            _podwatch.remove_callback(h)
        self._thread.join(timeout=10.0)
        global _ACTIVE
        with _ACTIVE_LOCK:
            if _ACTIVE is self:
                _ACTIVE = None

    # -- the recovery driver (one thread) -------------------------------

    def _run(self):
        while True:
            self._wake.wait()
            if self._stop.is_set():
                return
            self._wake.clear()
            with self._lock:
                deaths = set(self._pending_deaths)
                rejoins = set(self._pending_rejoins)
            if not deaths and not rejoins:
                self._recovered.set()
                continue
            try:
                self._recover(deaths, rejoins)
            except Exception as exc:    # noqa: BLE001 — recorded giveup
                self.failed = exc
                self._counters.add("giveups")
                _obs.event("supervisor.giveup",
                           error=type(exc).__name__)
                _podwatch.clear_quiesce()   # held retries must not
                #                             wait on a dead recovery
                with self._lock:
                    self._pending_deaths.clear()
                    self._pending_rejoins.clear()
                self._recovered.set()   # wait_recovered re-raises

    def _members(self, rejoins):
        """The deterministic next-cluster membership: surviving
        incumbent ranks (ascending, quarantine excluded) then rejoiner
        identities (sorted).  Every survivor computes the same list;
        the coordinator's copy is the one the plan publishes."""
        alive = [p for p in _podwatch.alive_peers()
                 if self._ident_of(p) not in self._quarantine]
        members = [["i", int(p)] for p in alive]
        members += [["r", ident] for ident in sorted(rejoins)]
        return members

    def _recover(self, deaths, rejoins):
        """One full recovery: pause, (for growth) quiesce and drain
        in-flight pod streams, then the elect → plan → reform attempt
        loop with exponential backoff.  A death arriving mid-loop is
        folded into the next attempt's membership (the 'second failure
        mid-reform' contract)."""
        t0 = _clock()
        # a NEW recovery supersedes a past giveup: held retries and
        # blocked submitters must wait for THIS outcome, not abort on
        # the stale error (failed is re-set by _run if this one also
        # exhausts its budget)
        self.failed = None
        self._tried_gens = set()       # plans already driven (and
        #                                failed) this recovery — never
        #                                re-adopt one; the coordinator
        #                                publishes a fresh generation
        #                                every attempt
        reason = "peer death %s" % sorted(deaths) if deaths else \
            "rejoin %s" % sorted(rejoins)
        if self.on_pause is not None:
            try:
                self.on_pause(reason)
            except Exception:           # noqa: BLE001
                pass
        if rejoins and not deaths:
            # growth must not abandon a healthy in-flight collective
            # schedule: ask streams to stop at a slab-boundary
            # checkpoint, then wait for this process to go idle (the
            # quiesce gate or natural completion gets it there)
            _podwatch.request_quiesce("rejoin %s" % sorted(rejoins))
            busy_deadline = _clock() + (_DEF_DRAIN or max(
                60.0, 10 * (_podwatch.deadline() or 5.0)))
            while _podwatch.pod_busy() and _clock() < busy_deadline \
                    and not self._stop.is_set():
                self._stop.wait(0.05)
                with self._lock:        # a death mid-quiesce switches
                    if self._pending_deaths - deaths:  # to shrink mode
                        break
        from bolt_tpu.parallel import multihost as _multihost
        if rejoins and not deaths and _podwatch.pod_busy() \
                and not self._stop.is_set():
            with self._lock:
                second = bool(self._pending_deaths - deaths)
            if not second:
                # the pod never went idle within the drain budget —
                # e.g. an UNCHECKPOINTED stream can never observe the
                # quiesce request (the gate rides the checkpoint
                # write).  Reforming up now would tear down the XLA
                # backends under the live collective schedule, so
                # DEFER the growth: resume the pod untouched; the
                # rejoiner's attach() times out pointedly and its
                # next doorbell rings through (latch reset below).
                _podwatch.clear_quiesce()
                _obs.event("supervisor.rejoin_deferred",
                           idents=sorted(rejoins))
                with self._lock:
                    self._pending_rejoins -= rejoins
                for ident in rejoins:
                    _podwatch.rejoin_reset(ident)
                if self.on_resume is not None:
                    try:
                        n = int(_multihost.process_count())
                    except Exception:  # noqa: BLE001
                        n = 0
                    try:
                        self.on_resume({"nproc": n, "rejoined": [],
                                        "gen": self._gen,
                                        "deferred": sorted(rejoins)})
                    except Exception:  # noqa: BLE001
                        pass
                self._recovered.set()
                return
        w = _podwatch._WATCH
        if w is not None:
            self._probe = (w.nproc, w.pid,
                           getattr(w.transport, "path", None),
                           w.interval, w.timeout)
        delay = self.backoff
        attempt = 0
        while not self._stop.is_set():
            try:
                _chaos.hit("supervisor.elect")
                with self._lock:        # fold late arrivals in
                    deaths |= self._pending_deaths
                    rejoins |= {r for r in self._pending_rejoins
                                if r not in self._quarantine}
                members = self._members(rejoins)
                if not members:
                    raise SuperviseError(
                        "no surviving members to reform onto "
                        "(deaths %s, quarantine %s)"
                        % (sorted(deaths), sorted(self._quarantine)))
                plan = self._drive_plan(members)
                info = self._reform(plan, _multihost)
            except Exception as exc:    # noqa: BLE001 — one attempt
                attempt += 1
                if attempt > self.retries:
                    raise SuperviseError(
                        "supervised recovery abandoned after %d "
                        "attempt(s): %s" % (attempt, exc)) from exc
                self._counters.add("backoffs")
                _obs.event("supervisor.backoff", attempt=attempt,
                           delay=round(delay, 3),
                           error=type(exc).__name__)
                self._stop.wait(delay)
                delay *= 2
                self._reprobe()
                continue
            break
        if self._stop.is_set():
            return
        # success: bookkeeping, marker hygiene, resume
        _podwatch.clear_quiesce()
        with self._lock:
            self._gen = plan["gen"]
            self._pending_deaths -= deaths
            self._pending_rejoins -= rejoins
            self._joined |= rejoins
            # new rank -> identity: plan order IS the new rank order;
            # incumbents carry their identity from the OLD rank map
            self._ident_by_rank = {
                idx: (m[1] if m[0] == "r" else self._ident_of(m[1]))
                for idx, m in enumerate(plan["members"])}
            for ident in rejoins:
                strikes = self._strikes.get(ident, 0)
                if strikes >= self.quarantine_after:
                    self._quarantine.add(ident)
        tr = _podwatch.transport()
        if tr is not None:
            for ident in rejoins:       # consumed doorbells; removal
                try:                    # races across members are benign
                    tr.rejoin_clear(ident)
                except Exception:       # noqa: BLE001
                    pass
        self._counters.update(reforms=1, rejoins=len(rejoins),
                              supervise_seconds=_clock() - t0)
        with self._lock:
            self._last["last_recovery_seconds"] = _clock() - t0
        self.failed = None
        if self.on_resume is not None:
            try:
                self.on_resume(info)
            except Exception:           # noqa: BLE001
                pass
        self._recovered.set()

    def _drive_plan(self, members):
        """Elect + publish/fetch the reform plan for ``members``.  The
        coordinator is the LOWEST surviving incumbent rank; it
        allocates a fresh port and publishes {addr, members, epoch,
        gen} through the transport; followers poll the same generation
        until it lands.  Returns the plan dict."""
        tr = _podwatch.transport()
        if tr is None:
            raise SuperviseError(
                "no liveness transport to carry the reform plan (the "
                "watch is not running); supervision needs "
                "BOLT_POD_HB_DIR or a live KV store")
        incumbents = [m[1] for m in members if m[0] == "i"]
        me = self._my_rank()
        deadline = _podwatch.deadline() or 5.0
        if incumbents and me == incumbents[0]:
            gens = tr.plan_gens()
            gen = (max(gens) if gens else self._gen) + 1
            # epoch strides by 2: the +1 slot between plan epochs is
            # reserved for the liveness RE-PROBE after a failed
            # attempt (_reprobe), so probe beats can never pollute the
            # next cluster's namespace
            plan = {"addr": "%s:%d" % (self.host, free_port()),
                    "members": members,
                    "epoch": int(_podwatch.epoch()) + 2,
                    "gen": int(gen)}
            tr.plan_set(gen, json.dumps(plan))
            self._tried_gens.add(int(gen))
            return plan
        # follower: adopt the newest plan NEWER than the last one this
        # member drove that names it.  The floor must be self._gen + 1,
        # not max(existing)+1 — the coordinator detects the death on
        # its own clock and its plan may already be on the transport
        # before this member's latch fires (a later floor would skip
        # that plan forever and burn the whole retry budget waiting
        # for a generation nobody will publish)
        floor = self._gen + 1
        stall = _clock() + max(4 * deadline, 10.0)
        while _clock() < stall and not self._stop.is_set():
            for g in reversed(tr.plan_gens()):
                if g < floor:
                    break
                if g in self._tried_gens:
                    continue
                raw = tr.plan_get(g)
                if raw is None:
                    continue
                plan = json.loads(raw)
                if ["i", me] in plan["members"]:
                    self._tried_gens.add(int(g))
                    return plan
            self._stop.wait(0.05)
        raise SuperviseError(
            "no reform plan published for generation >= %d within "
            "%.1fs (coordinator rank %s may have died mid-reform)"
            % (floor, max(4 * deadline, 10.0),
               incumbents[0] if incumbents else None))

    def _my_rank(self):
        """This member's rank per the liveness watch.  Refuses to
        guess when the watch is down (a rank-0 default would let a
        non-zero survivor impersonate the coordinator and publish a
        conflicting plan): the attempt fails, the backoff loop
        re-probes, and the next attempt sees a live watch or gives
        up loudly."""
        w = _podwatch._WATCH
        if w is None:
            raise SuperviseError(
                "liveness watch is down mid-recovery — cannot "
                "determine this member's rank (the re-probe before "
                "the next attempt restarts it)")
        return w.pid

    def _reform(self, plan, _multihost):
        """Drive ``multihost.reform`` from one plan; returns the
        resume info dict."""
        me = self._my_rank()
        try:
            new_pid = plan["members"].index(["i", me])
        except ValueError:
            raise SuperviseError(
                "this process (rank %d) is not in the reform plan %s"
                % (me, plan["members"]))
        rejoined = [m[1] for m in plan["members"] if m[0] == "r"]
        sp = _obs.begin("supervisor.reform", gen=plan["gen"],
                        nproc=len(plan["members"]))
        t0 = _clock()
        try:
            _multihost.reform(plan["addr"], len(plan["members"]),
                              process_id=new_pid, epoch=plan["epoch"],
                              init_timeout=self.init_timeout
                              if self.init_timeout is not None
                              else _fastfail_init_timeout())
        finally:
            _obs.end(sp)
        with self._lock:
            self._last["last_reform_seconds"] = _clock() - t0
        return {"nproc": len(plan["members"]), "rejoined": rejoined,
                "gen": plan["gen"], "pid": new_pid}

    def _reprobe(self):
        """After a failed reform attempt every survivor's watch is
        down (``multihost.reform`` stops it before the bring-up) —
        restart a liveness PROBE on the shared ``epoch()+1`` slot so
        the next attempt's membership reflects who is still actually
        alive: the second victim never beats on the probe epoch, drops
        out of ``alive_peers`` and fires the death callback (strike
        counted).  Every survivor lands on the same probe epoch
        because their epoch counters were synced by the last common
        watch and plan epochs stride by 2.  Best-effort: with no
        captured watch geometry (or a KV transport whose store died)
        the next attempt just fails fast again and burns a retry."""
        if _podwatch.active() or self._probe is None:
            return
        nproc, pid, path, interval, timeout = self._probe
        try:
            _podwatch.start(nproc, pid, dir=path, interval=interval,
                            timeout=timeout,
                            epoch=int(_podwatch.epoch()) + 1)
        except Exception:             # noqa: BLE001 — probe is advisory
            return
        # give every survivor's probe beats one deadline to land (the
        # scan latches never-seen peers dead after `timeout` anyway)
        self._stop.wait(timeout + 2 * interval)


# ---------------------------------------------------------------------
# module doors
# ---------------------------------------------------------------------

def active():
    """The process's installed :class:`Supervisor`, or ``None``."""
    return _ACTIVE


def attach(identity, dir=None, host=None, timeout=120, retries=None,
           backoff=None):
    """The REJOINER's door: announce this (restarted or replacement)
    process to a running pod, wait for the incumbents' reform plan,
    join the re-expanded cluster, and return a running
    :class:`Supervisor` for it (a member that just proved pods flap
    should supervise like any other).

    ::

        sup = supervisor.attach("worker-7b", dir="/shared/hb")
        # ... this process is now rank k of the grown pod; re-submit
        # the pod pipeline and it resumes from the shared checkpoint

    ``identity`` is any string unique among concurrent rejoiners;
    ``dir`` the shared transport directory (default
    ``BOLT_POD_HB_DIR``).  Raises :class:`SuperviseError` when no plan
    naming this identity lands within ``timeout`` seconds (the pod may
    be gone, or this identity is quarantined)."""
    # the transport sanitizes marker filenames, so the incumbents'
    # plan names the SANITIZED identity — compare with the same form
    # or an identity like "worker:7" could never match its own plan
    identity = _podwatch._safe_ident(identity)
    tr = _podwatch.rejoin(identity, dir=dir)
    known = set(tr.plan_gens())
    t0 = _clock()
    plan = None
    while _clock() - t0 < timeout:
        for g in reversed(tr.plan_gens()):
            if g in known:
                break
            raw = tr.plan_get(g)
            if raw is None:
                continue
            cand = json.loads(raw)
            if ["r", identity] in cand["members"]:
                plan = cand
                break
        if plan is not None:
            break
        time.sleep(0.05)
    if plan is None:
        raise SuperviseError(
            "rejoin %r: no reform plan named this identity within "
            "%.0fs — the pod may be gone, idle with supervision off, "
            "or this identity is quarantined" % (identity, timeout))
    from bolt_tpu.parallel import multihost as _multihost
    new_pid = plan["members"].index(["r", identity])
    sp = _obs.begin("supervisor.reform", gen=plan["gen"],
                    nproc=len(plan["members"]), rejoiner=1)
    try:
        _multihost.reform(plan["addr"], len(plan["members"]),
                          process_id=new_pid, epoch=plan["epoch"],
                          init_timeout=_fastfail_init_timeout())
    finally:
        _obs.end(sp)
    # seed the new member's rank -> identity map from the plan it
    # joined by, so ITS strike/quarantine attribution starts correct
    ident_map = {idx: (m[1] if m[0] == "r" else "i%d" % m[1])
                 for idx, m in enumerate(plan["members"])}
    # seed gen/joined from the plan too: the follower adoption floor
    # is _gen + 1, so a fresh supervisor at gen 0 could re-adopt a
    # RETAINED stale plan generation on its next recovery (sweep_epochs
    # keeps the last two) and reform against a dead coordinator; and
    # this plan's rejoiners are members now — their sweep-lag doorbell
    # duplicates must be dropped like the incumbents drop them
    return Supervisor(retries=retries, backoff=backoff, host=host,
                      ident_map=ident_map, gen=plan["gen"],
                      joined=[m[1] for m in plan["members"]
                              if m[0] == "r"])
