"""The out-of-core shuffle planner: slab-wise re-axis for streamed
``swap`` (ISSUE 18).

``swap(kaxes, vaxes)`` is THE signature Bolt operation (the reference's
chunk → Spark shuffle → unchunk, SURVEY §3.3) — and the one core op a
streamed source could not reach without materialising fully.  This
module plans the two-phase pipeline that closes the gap:

* **phase 1 (re-bucket)**: every input slab streams up through the
  normal uploader path, and ONE compiled program per slab applies the
  pre-swap stage chain and the swap's transpose, producing that slab's
  contribution to the output — the full new-key extent, with the
  slab's input records along the axis the old record axis landed on
  (``j0 = perm.index(0)``).  On a pod the program runs under
  ``shard_map`` with an explicit ``lax.all_to_all`` (split the new
  record axis, concatenate at ``j0``), so each slab costs exactly one
  collective; single-process the transpose plus a sharding constraint
  lets GSPMD insert the local permute.
* **phase 2 (re-assemble)**: transposed slabs either stay RESIDENT
  (concatenated along ``j0`` into the swapped array when the output
  fits the budget) or SPILL to encoded bucket files — ``out_block``
  new-key records per bucket — which a fresh callback
  :class:`~bolt_tpu.stream.StreamSource` then streams through the SAME
  slab-program machinery as any other source (Spark's shuffle-spill
  reincarnated on the donation ring).

Parity is by construction: phase 1 traces the SAME
``jnp.transpose(perm)`` expression the materialised ``_do_swap``
compiles and the SAME ``_stage_apply`` bodies the materialised replay
uses, and transpose/split/concatenate are pure data movement — so a
streamed swap is bit-identical to the materialised one, resident or
spilled, single-process or pod.

The planner (:func:`plan_shuffle`) is consulted both by the executor
(``stream.resolve_swaps``) and abstractly by ``analysis.check`` (the
BLT017 forecast), so the forecast and the measured decision cannot
drift: both read the same resident/spill rule off the same budget.
"""

import numpy as np

import jax
import jax.numpy as jnp

from bolt_tpu import engine as _engine
from bolt_tpu.parallel import multihost as _multihost
from bolt_tpu.parallel import sharding as _sharding
from bolt_tpu.utils import prod


class ShufflePlan:
    """The static description of one streamed-swap resolution.

    ``resident`` is the phase-2 decision: keep every transposed slab in
    HBM and concatenate (True), or spill encoded bucket files and
    re-stream them (False).  ``alltoall_bytes`` is the planner's
    cross-device traffic model: the bytes that must cross device
    boundaries during phase 1 (0 when the record axis stays leading —
    a pure local permute)."""

    __slots__ = ("in_shape", "dtype", "split", "perm", "new_split",
                 "out_shape", "j0", "slab", "nslabs", "out_block",
                 "nbuckets", "total_bytes", "slab_bytes", "budget",
                 "resident", "spill_dir", "alltoall_bytes", "sharded")

    def __init__(self, **kw):
        for name in self.__slots__:
            setattr(self, name, kw[name])

    def describe(self):
        """One-line human summary (the BLT017 message body)."""
        mb = 1024.0 * 1024.0
        mode = "resident" if self.resident else (
            "spill to %s" % (self.spill_dir or "<no spill dir>"))
        return ("shuffle plan: %d slab%s -> %s (%.1f MiB working set, "
                "budget %s, %d bucket%s x %d records, all-to-all "
                "~%.1f MiB)"
                % (self.nslabs, "s" if self.nslabs != 1 else "", mode,
                   self.total_bytes / mb,
                   ("%.1f MiB" % (self.budget / mb))
                   if self.budget is not None else "unbounded",
                   self.nbuckets, "s" if self.nbuckets != 1 else "",
                   self.out_block, self.alltoall_bytes / mb))


def _axis0_device_width(mesh, shape, split):
    """How many devices shard the LEADING key axis of ``shape`` under
    the key sharding — the divisor phase-2 bucket extents must honour
    so bucket slabs reshard cleanly."""
    if mesh is None:
        return 1
    spec = _sharding.key_spec(mesh, tuple(shape), split)
    names = _sharding.spec_names(spec[0] if len(spec) else None)
    return prod([mesh.shape[n] for n in names]) if names else 1


def _pick_out_block(extent, target_rows, mult):
    """Largest divisor of ``extent`` that is a multiple of ``mult`` and
    no larger than ``target_rows`` — the phase-2 bucket extent.  Falls
    back to the SMALLEST valid divisor when nothing fits under the
    target (better one oversized bucket than a refused plan); ``None``
    when no divisor honours ``mult`` at all."""
    mult = max(1, int(mult))
    divisors = [d for d in range(1, extent + 1)
                if extent % d == 0 and d % mult == 0]
    if not divisors:
        return None
    under = [d for d in divisors if d <= max(target_rows, 1)]
    return under[-1] if under else divisors[0]


def plan_shuffle(staged_shape, dtype, split, perm, new_split, mesh,
                 slab, budget, spill_dir):
    """Plan one streamed-swap resolution over the POST-pre-stage
    geometry.

    ``staged_shape``/``dtype``/``split`` describe the stream AFTER the
    stages recorded before the swap (the value the swap's transpose
    actually sees); ``perm``/``new_split`` are the swap's permutation
    exactly as ``tpu/array.py :: _do_swap`` builds them; ``slab`` is
    the input records per slab; ``budget`` the resident ceiling in
    bytes (``None`` = unbounded → always resident); ``spill_dir``
    where bucket files would land.  Raises the pointed pod-geometry
    errors HERE, before any thread starts, mirroring BLT012."""
    staged_shape = tuple(int(s) for s in staged_shape)
    perm = tuple(int(p) for p in perm)
    out_shape = tuple(staged_shape[p] for p in perm)
    j0 = perm.index(0)
    itemsize = np.dtype(dtype).itemsize
    total_bytes = prod(out_shape) * itemsize
    n = staged_shape[0]
    nslabs = max(1, -(-n // max(slab, 1)))
    slab_bytes = min(slab, n) * prod(staged_shape[1:]) * itemsize
    sharded = _multihost.mesh_process_count(mesh) > 1

    # phase-2 bucket extent along the NEW leading key axis: must divide
    # the extent (buckets tile it exactly), honour the output key
    # sharding's device width (bucket slabs reshard cleanly — the
    # BLT012 analog), and on pods divide the per-process range (each
    # bucket wholly owned by ONE process, so spill files never cross
    # host boundaries)
    out_n = out_shape[0]
    dwidth = _axis0_device_width(mesh, out_shape, new_split)
    extent = out_n
    if sharded:
        nproc = _multihost.mesh_process_count(mesh)
        if out_n % nproc != 0:
            raise ValueError(
                "streamed swap on a %d-process pod needs the new "
                "leading key extent (%d) divisible by the process "
                "count — repartition or materialise the swap instead"
                % (nproc, out_n))
        extent = out_n // nproc
    target = max(1, (slab_bytes // max(
        prod(out_shape[1:]) * itemsize, 1)) or 1)
    out_block = _pick_out_block(extent, target, dwidth)
    if out_block is None:
        # nothing divides cleanly: fall back to whole-extent buckets
        out_block = extent
    nbuckets = out_n // out_block

    # the all-to-all traffic model: when the record axis stays leading
    # (perm[0] == 0) every record keeps its device and nothing crosses;
    # otherwise each device keeps 1/d of what it holds and ships the
    # rest — the standard all-to-all volume over the d devices that
    # shard the input record axis
    d_in = _axis0_device_width(mesh, staged_shape, split)
    alltoall_bytes = 0 if perm[0] == 0 or d_in <= 1 else int(
        round(total_bytes * (d_in - 1) / d_in))

    resident = budget is None or total_bytes + slab_bytes <= budget
    return ShufflePlan(
        in_shape=staged_shape, dtype=np.dtype(dtype), split=int(split),
        perm=perm, new_split=int(new_split), out_shape=out_shape, j0=j0,
        slab=int(slab), nslabs=int(nslabs), out_block=int(out_block),
        nbuckets=int(nbuckets), total_bytes=int(total_bytes),
        slab_bytes=int(slab_bytes),
        budget=None if budget is None else int(budget),
        resident=bool(resident), spill_dir=spill_dir,
        alltoall_bytes=int(alltoall_bytes), sharded=bool(sharded))


def _pod_axes_or_refuse(mesh, slab_shape, split, perm, out_slab_shape,
                        new_split):
    """The pod re-bucket geometry check: the explicit ``all_to_all``
    form needs the input record axis's mesh axes to be exactly the
    ones the OUTPUT leading key axis shards over (the collective splits
    the new record extent over the same devices it gathers the old one
    from), and the new leading axis must come from a REPLICATED value
    axis (its full extent is local).  Returns the mesh-axis name tuple;
    raises the pointed refusal otherwise."""
    in_spec = _sharding.key_spec(mesh, slab_shape, split)
    axes_in = _sharding.spec_names(in_spec[0] if len(in_spec) else None)
    out_spec = _sharding.key_spec(mesh, out_slab_shape, new_split)
    axes_out = _sharding.spec_names(out_spec[0] if len(out_spec)
                                    else None)
    if perm[0] == 0:
        return ()                     # no cross-device movement
    if perm[0] < split:
        raise ValueError(
            "streamed swap on a pod needs the new leading key axis to "
            "come from a value axis or stay the record axis; key axis "
            "%d moving to the front has per-process layout this "
            "executor does not reshard — materialise the swap instead"
            % (perm[0],))
    if axes_in != axes_out:
        raise ValueError(
            "streamed swap on a pod needs the output key sharding to "
            "reuse the input record axis's mesh axes (got %r -> %r); "
            "materialise the swap instead" % (axes_in, axes_out))
    return axes_in


def rebucket_program(plan, pre_stages, mesh, codec_obj, raw_dtype,
                     raw_slab_shape, delta_ok):
    """The ONE compiled phase-1 program each input slab runs: fused
    codec decode (when streaming rode a codec), the pre-swap stage
    chain, and the swap's transpose — the EXACT expression the
    materialised ``swap`` compiles, so parity holds by construction.

    ``raw_slab_shape`` is the UPLOADED slab's shape (wire dtype under a
    codec); the program's output is that slab's transposed block: the
    full new-key extent with the slab's records at axis ``plan.j0``,
    constrained to the output key sharding.  On pods the body runs
    under ``shard_map`` with ONE explicit ``lax.all_to_all`` per slab
    (``split_axis=0`` of the new layout, ``concat_axis=j0``, tiled) —
    the TPU-native form of the reference's cluster-wide shuffle.
    Engine-cached per (stages, slab geometry, perm, codec, topology):
    uniform slabs compile exactly once per variant per process."""
    split = plan.split
    perm = plan.perm
    j0 = plan.j0
    slab_rows = raw_slab_shape[0]
    out_slab_shape = tuple(
        slab_rows if i == j0 else plan.out_shape[i]
        for i in range(len(plan.out_shape)))
    key = ("stream-shuffle", pre_stages, tuple(raw_slab_shape),
           str(raw_dtype), split, perm, plan.new_split, mesh,
           _multihost.topology_token() if plan.sharded else None,
           codec_obj.name if codec_obj is not None else None)

    def build():
        from bolt_tpu.stream import _stage_apply
        from bolt_tpu.tpu.array import _constrain

        def body(data):
            if codec_obj is None:
                x = data
            elif codec_obj.sidecar:
                x = codec_obj.decode(data[0], data[1:], raw_dtype,
                                     delta_ok)
            else:
                x = codec_obj.decode(data, (), raw_dtype, delta_ok)
            for stg in pre_stages:
                x = _stage_apply(stg, split, x)
            return jnp.transpose(x, perm)

        if not plan.sharded:
            def run(data):
                return _constrain(body(data), mesh, plan.new_split)
            return jax.jit(run, donate_argnums=(0,))

        from jax.sharding import PartitionSpec
        from bolt_tpu import _compat
        from bolt_tpu.parallel.sharding import key_spec
        staged_slab = tuple(
            slab_rows if i == 0 else plan.in_shape[i]
            for i in range(len(plan.in_shape)))
        axes = _pod_axes_or_refuse(mesh, staged_slab, split, perm,
                                   out_slab_shape, plan.new_split)

        def shard_body(data):
            y = body(data)
            if axes:
                # one collective per slab: split the (locally full) new
                # record axis over the devices that held the old one,
                # concatenating each device's incoming pieces at j0 —
                # device order equals global record order, so the glued
                # global equals the global transpose bit-for-bit
                for name in axes:
                    y = jax.lax.all_to_all(y, name, split_axis=0,
                                           concat_axis=j0, tiled=True)
            return y

        in_specs = key_spec(mesh, staged_slab, split)
        out_entries = [None] * len(out_slab_shape)
        out_entries[0] = (axes[0] if len(axes) == 1 else tuple(axes)) \
            if axes else None
        if not axes:
            # record axis stays leading: its sharding is unchanged
            out_entries[j0] = in_specs[0] if len(in_specs) else None
        body_sm = _compat.shard_map(
            shard_body, mesh, in_specs=in_specs,
            out_specs=PartitionSpec(*out_entries), check_vma=False)
        return jax.jit(body_sm, donate_argnums=(0,))

    return _engine.get(key, build)


def concat_program(plan, part_shapes, mesh):
    """Glue phase-1 transposed slabs into the RESIDENT swapped array:
    one concatenate along ``j0``, inputs donated (the parts are
    consumed — at HBM-filling sizes the parts and the result cannot
    coexist twice), output constrained to the new key sharding."""
    key = ("stream-shuffle-concat", tuple(part_shapes), str(plan.dtype),
           plan.j0, plan.new_split, mesh,
           _multihost.topology_token() if plan.sharded else None)

    def build():
        from bolt_tpu.tpu.array import _constrain

        def run(*parts):
            out = parts[0] if len(parts) == 1 \
                else jnp.concatenate(parts, axis=plan.j0)
            return _constrain(out, mesh, plan.new_split)
        return jax.jit(run, donate_argnums=tuple(range(len(part_shapes))))

    return _engine.get(key, build)
