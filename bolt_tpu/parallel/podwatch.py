"""Pod liveness and collective watchdogs: peer-death detection for
multi-process streams (ISSUE 11).

A pod is N OS processes cooperating through collectives, and a
``kill -9`` of ONE of them leaves the survivors inside a gloo
rendezvous that can never complete — historically an infinite hang (or,
worse, the coordination service's default missed-heartbeat handler
``LOG(QFATAL)``-ing the survivors too).  This module converts peer
death into a fast, NAMED, recoverable event:

* a **heartbeat thread** per process beats a shared transport every
  ``BOLT_POD_HEARTBEAT`` seconds and watches every peer's beats; a peer
  whose beat goes stale past ``BOLT_POD_TIMEOUT`` is declared DEAD —
  latched, callback-fanned (:func:`on_peer_death`), visible through
  :func:`peers`/:func:`dead_peers`.  Two transports: the
  ``jax.distributed`` KV store (``_compat.distributed_client`` — zero
  extra infrastructure on a real pod) and a shared-directory file
  transport (``BOLT_POD_HB_DIR`` — the localhost harness's choice, and
  the one that keeps working when the COORDINATOR process is the
  victim);
* a **collective watchdog**: :func:`wait_ready` polls a dispatched
  value's readiness instead of blocking in the runtime, so a dead peer
  raises a pointed :class:`PeerLostError` — naming the dead process
  index and the in-flight slab — instead of hanging the survivor;
  :func:`reraise` classifies the FAST failure mode (on localhost TCP a
  dead peer fails collectives with a gloo transport error within
  milliseconds) into the same ``PeerLostError``;
* a **watchdog barrier**: :func:`barrier` is a transport-level
  rendezvous with liveness checks — the checkpoint fences of
  ``bolt_tpu.checkpoint`` ride it on pods, so a barrier against a dead
  peer fails deterministically within ~the heartbeat timeout instead
  of blocking in ``sync_global_devices`` forever;
* **reform notification**: ``multihost.reform`` (the shrink-and-resume
  door) calls :func:`notify_reform` once the runtime is rebuilt on the
  survivors; :func:`on_reform` subscribers (``bolt_tpu.serve`` drains
  admission on peer death and resumes here) pick the pod back up;
* a **REJOIN door** (ISSUE 12): a restarted or replacement process
  announces itself through the transport (:func:`rejoin` — an
  epoch-agnostic marker at the transport root, because the newcomer
  does not know the incumbents' epoch); the watch's scan fires
  :func:`on_rejoin` subscribers (``parallel.supervisor`` reforms the
  pod UP to the larger topology).  The supervisor's reform **plan**
  (coordinator address, member list, new epoch) also rides the
  transport (``plan_set``/``plan_get``), so no out-of-band agreement
  is ever needed;
* a **readiness rendezvous** (:func:`ready_rendezvous`) closing the
  pre-collective death bound: the first collective dispatch of a pod
  stream used to block in gloo's ~30s connect when a peer died before
  ever dispatching — now every process confirms liveness over the
  heartbeat transport right before its first dispatch, so a peer dead
  at dispatch time raises :class:`PeerLostError` within ~2x
  ``BOLT_POD_TIMEOUT`` instead;
* a **quiesce gate** (:func:`request_quiesce` / :func:`quiesce_gate`):
  the supervisor asks in-flight pod streams to stop at a
  slab-boundary checkpoint so the pod can reform to a LARGER topology
  mid-stream; the decision is made by process 0 and propagated through
  the transport behind the checkpoint barrier, so every process raises
  the same :class:`PodQuiesceError` at the same watermark.

The watchdog defaults OFF single-process (``deadline()`` is ``None``
until :func:`start` runs, and ``multihost.initialize`` only starts it
on a multi-process runtime); ``BOLT_POD_TIMEOUT=0`` disables it
explicitly.  Deterministic fault injection rides the
``podwatch.heartbeat`` chaos seam (``bolt_tpu._chaos``): ``kill``
action = the preemption test, ``raise`` = a sick process whose beats
stop landing.

Lint: this module is a blessed home of raw thread construction
(BLT108, next to ``stream.py``/``serve.py``); it touches NO
``jax.distributed`` symbols itself (BLT110 — topology and the KV
client arrive from ``multihost``/``_compat``).
"""

import contextlib
import glob
import os
import threading
import time

from bolt_tpu import _chaos
from bolt_tpu import _lockdep
from bolt_tpu.obs import trace as _obs
from bolt_tpu.obs.trace import clock as _clock

# ---------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------

# the watchdog deadline: how long a peer's heartbeat may go stale before
# it is declared dead (and how long a guarded sync waits before blaming
# a dead peer).  0 disables the watchdog even on pods.  The default is
# deliberately a few seconds: fast enough that "kill -9 one pod process"
# is detected well inside any human's patience, slow enough that a GC
# pause or a compile burst on a peer is not a false positive.
_DEF_TIMEOUT = float(os.environ.get("BOLT_POD_TIMEOUT", "5"))

# heartbeat cadence; default derives from the timeout (>= 4 beats must
# go missing before a peer is declared dead)
_ENV_INTERVAL = os.environ.get("BOLT_POD_HEARTBEAT")

# shared-directory transport (the harness form); unset = the
# jax.distributed KV store when available
_ENV_HB_DIR = os.environ.get("BOLT_POD_HB_DIR")

# a barrier where every peer is ALIVE but some never arrives is a code
# divergence, not a death — cap the wait so it surfaces pointedly
_BARRIER_STALL_X = 10.0


class PeerLostError(RuntimeError):
    """A pod peer died while a collective, barrier or streamed slab was
    in flight.  ``peer`` is the dead process index (or ``None`` when
    the transport error arrived before the liveness layer could name
    it), ``slab`` the in-flight slab index (or ``None``), ``phase``
    the operation the watchdog was guarding.  Retryable: the serving
    layer treats it as transient (``submit(retries=)`` re-attempts once
    the pod reforms), and ``multihost.reform`` + a checkpointed re-run
    recover the stream."""

    def __init__(self, message, peer=None, slab=None, phase=None):
        super().__init__(message)
        self.peer = peer
        self.slab = slab
        self.phase = phase


class PodQuiesceError(PeerLostError):
    """A pod stream stopped deliberately at a slab-boundary checkpoint
    because the supervisor requested a QUIESCE (a rejoined process is
    waiting to be folded back in — ISSUE 12).  No peer is dead
    (``peer`` is ``None``); the run's checkpoint at ``slab`` retired
    slabs is the resume point.  Retryable exactly like a peer loss:
    the serving layer holds the re-attempt behind the admission drain
    until the supervisor's reform-UP completes, then the re-run
    resumes bit-identically on the larger pod."""


def _lost_message(peers_, phase, slab):
    who = ("process %s" % ", ".join(str(p) for p in peers_)
           if peers_ else "a pod peer")
    where = " during %s" % phase if phase else ""
    slab_s = " (in-flight slab %d)" % slab if slab is not None else ""
    return ("pod peer lost: %s died%s%s; surviving processes abort "
            "deterministically instead of hanging in the dead "
            "collective — reform the pod (multihost.reform) and re-run "
            "to resume from the last consistent checkpoint"
            % (who, where, slab_s))


# transport-failure signatures a dead peer produces in the fast path
# (localhost TCP closes the socket at kill -9, so gloo collectives and
# coordination RPCs fail in milliseconds rather than hanging)
_TRANSPORT_SIGNS = (
    "gloo",
    "connection closed by peer",
    "connection refused",
    "connection reset",
    "socket closed",
    "coordination service",
    "distributed runtime",
    "heartbeat timeout",
    "unavailable",
)


def is_transport_error(exc):
    """Does ``exc`` look like a cross-process transport failure (the
    fast signature of a dead peer)?"""
    text = str(exc).lower()
    return any(sign in text for sign in _TRANSPORT_SIGNS)


# SECONDARY signatures: errors a dead peer produces one step removed
# from the transport — a failed async collective invalidates its
# output buffers, and the NEXT dispatch consuming them raises
# "Array has been deleted" instead of the underlying gloo error.
# These convert to PeerLostError only when the heartbeat actually
# latches a dead peer within the grace window (a genuine deleted-array
# bug must stay a deleted-array bug).
_SECONDARY_SIGNS = (
    "array has been deleted",
    "buffer has been deleted",
)


def is_secondary_sign(exc):
    """Could ``exc`` be the one-step-removed shape of a dead peer (an
    errored/donated buffer from a failed collective consumed by the
    next dispatch)?"""
    text = str(exc).lower()
    return any(sign in text for sign in _SECONDARY_SIGNS)


# ---------------------------------------------------------------------
# transports
# ---------------------------------------------------------------------

class FileTransport:
    """Shared-directory liveness transport: ``hb.p<pid>`` beat files
    (atomic rename) plus ``bar/`` arrival markers.  The harness (and
    any pod with shared storage) uses it; unlike the KV store it keeps
    working when process 0 — the coordination-service host — is the
    victim."""

    kind = "file"

    def __init__(self, path, epoch=0):
        self.path = os.fspath(path)
        self.epoch = int(epoch)
        os.makedirs(self.path, exist_ok=True)

    def _hb(self, pid):
        return os.path.join(self.path, "hb.e%d.p%d" % (self.epoch, pid))

    def beat(self, pid, seq):
        tmp = self._hb(pid) + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(int(seq)))
        os.replace(tmp, self._hb(pid))

    def read(self):
        """``{pid: seq}`` of every peer's latest landed beat."""
        out = {}
        for p in glob.glob(os.path.join(self.path,
                                        "hb.e%d.p*" % self.epoch)):
            if p.endswith(".tmp"):
                continue
            try:
                out[int(p.rsplit(".p", 1)[1])] = int(open(p).read() or 0)
            except (ValueError, OSError):
                pass                  # a beat mid-rename: next scan sees it
        return out

    def farewell(self, pid):
        tmp = self._hb(pid) + ".bye.tmp"
        with open(tmp, "w") as f:
            f.write("1")
        os.replace(tmp, self._hb(pid) + ".bye")

    def read_farewells(self):
        return {int(p[:-len(".bye")].rsplit(".p", 1)[1])
                for p in glob.glob(os.path.join(
                    self.path, "hb.e%d.p*.bye" % self.epoch))}

    def _bar(self, name, count, pid):
        return os.path.join(
            self.path, "bar",
            "e%d.%s.c%d.p%d" % (self.epoch, name, int(count), int(pid)))

    def barrier_mark(self, name, count, pid):
        path = self._bar(name, count, pid)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write("1")
        os.replace(tmp, path)

    def barrier_seen(self, name, count):
        prefix = self._bar(name, count, 0)[:-2]      # strip "p0"
        return {int(p.rsplit(".p", 1)[1])
                for p in glob.glob(prefix + "p*")
                if not p.endswith(".tmp")}

    def barrier_sweep(self, name, count, pid):
        """Remove OWN arrival markers two generations back (peers have
        long passed them; same-generation files must survive until
        every peer has seen them)."""
        if count < 2:
            return
        try:
            os.remove(self._bar(name, count - 2, pid))
        except OSError:
            pass

    # -- the rejoin door + reform-plan channel (ISSUE 12) --------------
    # These markers are EPOCH-AGNOSTIC (dir root): a restarted process
    # announcing itself cannot know the incumbents' current epoch, and
    # the reform plan is precisely how it learns the next one.

    def rejoin_mark(self, ident):
        path = os.path.join(self.path, "rejoin.%s" % _safe_ident(ident))
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write("1")
        os.replace(tmp, path)

    def read_rejoin_marks(self):
        return {os.path.basename(p)[len("rejoin."):]
                for p in glob.glob(os.path.join(self.path, "rejoin.*"))
                if not p.endswith(".tmp")}

    def rejoin_clear(self, ident):
        try:
            os.remove(os.path.join(self.path,
                                   "rejoin.%s" % _safe_ident(ident)))
        except OSError:
            pass

    def plan_set(self, gen, text):
        path = os.path.join(self.path, "plan.g%d.json" % int(gen))
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, path)

    def plan_get(self, gen):
        try:
            with open(os.path.join(self.path,
                                   "plan.g%d.json" % int(gen))) as f:
                return f.read()
        except OSError:
            return None

    def plan_gens(self):
        """Generations with a published plan (sorted)."""
        out = []
        for p in glob.glob(os.path.join(self.path, "plan.g*.json")):
            try:
                out.append(int(os.path.basename(p)[len("plan.g"):
                                                   -len(".json")]))
            except ValueError:
                pass
        return sorted(out)

    # -- the generic per-process note channel (schedule digests) -------
    # One small payload per (key, pid), last-writer-wins, read back as
    # {pid: text} — the exchange primitive multihost.verify_schedule
    # uses to compare dispatch-schedule digests across the pod.

    def note_set(self, key, pid, text):
        path = os.path.join(
            self.path, "note.e%d.%s.p%d" % (self.epoch,
                                            _safe_ident(key), int(pid)))
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, path)

    def note_read(self, key):
        out = {}
        for p in glob.glob(os.path.join(
                self.path,
                "note.e%d.%s.p*" % (self.epoch, _safe_ident(key)))):
            if p.endswith(".tmp"):
                continue
            try:
                with open(p) as f:
                    out[int(p.rsplit(".p", 1)[1])] = f.read()
            except (ValueError, OSError):
                pass                  # a note mid-rename: next poll sees it
        return out

    # -- the quiesce gate marker (single writer: process 0) ------------

    def quiesce_mark(self, watermark):
        path = os.path.join(self.path, "quiesce.e%d.w%d"
                            % (self.epoch, int(watermark)))
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write("1")
        os.replace(tmp, path)

    def quiesce_seen(self, watermark):
        return os.path.exists(os.path.join(
            self.path, "quiesce.e%d.w%d" % (self.epoch, int(watermark))))

    # -- marker hygiene (ISSUE 12 satellite: the shared dir must not
    # grow without bound across repeated reforms) ----------------------

    def sweep_epochs(self, keep_from):
        """Remove heartbeat/farewell/quiesce/barrier markers from
        epochs OLDER than ``keep_from`` (the previous epoch is kept one
        generation as a straggler grace), plus reform plans more than
        two generations stale.  Best-effort and idempotent — every
        reformed process calls it, removal races are benign."""
        keep_from = int(keep_from)
        for pat in ("hb.e*", "quiesce.e*"):
            for p in glob.glob(os.path.join(self.path, pat)):
                try:
                    ep = int(os.path.basename(p).split(".", 2)[1][1:])
                except (IndexError, ValueError):
                    continue
                if ep < keep_from:
                    try:
                        os.remove(p)
                    except OSError:
                        pass
        for p in glob.glob(os.path.join(self.path, "bar", "e*")):
            try:
                ep = int(os.path.basename(p).split(".", 1)[0][1:])
            except (IndexError, ValueError):
                continue
            if ep < keep_from:
                try:
                    os.remove(p)
                except OSError:
                    pass
        gens = self.plan_gens()
        for g in gens[:-2]:
            try:
                os.remove(os.path.join(self.path, "plan.g%d.json" % g))
            except OSError:
                pass

    def sweep_peer(self, pid):
        """Remove a DEAD peer's heartbeat/farewell markers (swept
        alongside ``checkpoint.stream_clear``'s shard sweep — a peer
        that died mid-run leaves beats nobody will ever advance)."""
        for p in glob.glob(os.path.join(self.path,
                                        "hb.e*.p%d" % int(pid))) \
                + glob.glob(os.path.join(self.path,
                                         "hb.e*.p%d.bye" % int(pid))):
            try:
                os.remove(p)
            except OSError:
                pass

    def stale_marker_count(self):
        """Markers from epochs before the current one (the hygiene
        observable the elastic bench gates at zero)."""
        n = 0
        for pat in ("hb.e*", "quiesce.e*"):
            for p in glob.glob(os.path.join(self.path, pat)):
                try:
                    ep = int(os.path.basename(p).split(".", 2)[1][1:])
                except (IndexError, ValueError):
                    continue
                if ep < self.epoch:
                    n += 1
        return n


def _safe_ident(ident):
    """Marker-filename-safe identity token."""
    return "".join(ch if (ch.isalnum() or ch in "._-") else "_"
                   for ch in str(ident)) or "anon"


class KVTransport:
    """Liveness over the ``jax.distributed`` KV store (the coordination
    service every pod already runs).  Beats are WRITE-ONCE keys
    (``hb/e<epoch>/p<pid>/<seq>`` — the store's overwrite rules never
    matter) with the previous beat deleted behind each new one, read
    back via a directory get.  Degrades loudly: a store that stops
    answering (the coordinator died) marks the transport failed, which
    the watch treats as a peer-loss signal."""

    kind = "kv"

    def __init__(self, client, epoch=0):
        self.client = client
        self.epoch = int(epoch)
        self.failed = None            # the store's last refusal

    def _pfx(self, pid=None):
        base = "bolt/hb/e%d/" % self.epoch
        return base if pid is None else base + "p%d/" % pid

    def beat(self, pid, seq):
        try:
            self.client.key_value_set(self._pfx(pid) + str(int(seq)), "1")
            if seq >= 2:
                self.client.key_value_delete(
                    self._pfx(pid) + str(int(seq) - 2))
        except Exception as exc:      # noqa: BLE001 — any store refusal
            self.failed = exc         # is a liveness signal, not a crash
            raise

    def read(self):
        try:
            items = self.client.key_value_dir_get(self._pfx())
        except Exception as exc:      # noqa: BLE001
            self.failed = exc
            raise
        out = {}
        for key, _ in items:
            try:
                _, rest = key.rsplit("/p", 1)
                pid_s, seq_s = rest.split("/", 1)
                pid, seq = int(pid_s), int(seq_s)
            except ValueError:
                continue
            if seq > out.get(pid, -1):
                out[pid] = seq
        return out

    def farewell(self, pid):
        try:
            self.client.key_value_set(self._pfx(pid) + "bye", "1")
        except Exception as exc:      # noqa: BLE001
            self.failed = exc

    def read_farewells(self):
        try:
            items = self.client.key_value_dir_get(self._pfx())
        except Exception as exc:      # noqa: BLE001
            self.failed = exc
            raise
        out = set()
        for key, _ in items:
            if key.endswith("/bye"):
                try:
                    out.add(int(key.rsplit("/p", 1)[1].split("/", 1)[0]))
                except ValueError:
                    pass
        return out

    def barrier_mark(self, name, count, pid):
        self.client.key_value_set(
            "bolt/bar/e%d/%s/c%d/p%d" % (self.epoch, name, int(count),
                                         int(pid)), "1")

    def barrier_seen(self, name, count):
        items = self.client.key_value_dir_get(
            "bolt/bar/e%d/%s/c%d/" % (self.epoch, name, int(count)))
        out = set()
        for key, _ in items:
            try:
                out.add(int(key.rsplit("/p", 1)[1]))
            except ValueError:
                pass
        return out

    def barrier_sweep(self, name, count, pid):
        if count < 2:
            return
        try:
            self.client.key_value_delete(
                "bolt/bar/e%d/%s/c%d/p%d" % (self.epoch, name,
                                             int(count) - 2, int(pid)))
        except Exception:             # noqa: BLE001 — sweep is best-effort
            pass

    # -- rejoin door / plan channel / quiesce marker (ISSUE 12).  Note
    # the practical limit the supervisor documents: the KV store lives
    # on the ORIGINAL coordinator, so a rejoin/plan exchange over KV
    # only works while that process survives — pods wanting automatic
    # re-expansion through a coordinator loss use the shared-dir
    # transport (BOLT_POD_HB_DIR). --------------------------------------

    def rejoin_mark(self, ident):
        try:
            self.client.key_value_set(
                "bolt/rejoin/%s" % _safe_ident(ident), "1")
        except Exception as exc:      # noqa: BLE001
            self.failed = exc

    def read_rejoin_marks(self):
        try:
            items = self.client.key_value_dir_get("bolt/rejoin/")
        except Exception:             # noqa: BLE001 — an unanswerable
            return set()              # store has no announcements
        return {key.rsplit("/", 1)[1] for key, _ in items}

    def rejoin_clear(self, ident):
        try:
            self.client.key_value_delete(
                "bolt/rejoin/%s" % _safe_ident(ident))
        except Exception:             # noqa: BLE001
            pass

    def plan_set(self, gen, text):
        self.client.key_value_set("bolt/plan/g%d" % int(gen), text)

    def plan_get(self, gen):
        try:
            items = self.client.key_value_dir_get("bolt/plan/")
        except Exception:             # noqa: BLE001
            return None
        want = "g%d" % int(gen)
        for key, val in items:
            if key.rsplit("/", 1)[1] == want:
                return val
        return None

    def plan_gens(self):
        try:
            items = self.client.key_value_dir_get("bolt/plan/")
        except Exception:             # noqa: BLE001
            return []
        out = []
        for key, _ in items:
            try:
                out.append(int(key.rsplit("/g", 1)[1]))
            except (IndexError, ValueError):
                pass
        return sorted(out)

    def note_set(self, key, pid, text):
        try:
            self.client.key_value_set(
                "bolt/note/e%d/%s/p%d" % (self.epoch, _safe_ident(key),
                                          int(pid)), text)
        except Exception as exc:      # noqa: BLE001
            self.failed = exc
            raise

    def note_read(self, key):
        try:
            items = self.client.key_value_dir_get(
                "bolt/note/e%d/%s/" % (self.epoch, _safe_ident(key)))
        except Exception:             # noqa: BLE001 — an unanswerable
            return {}                 # store has no notes yet
        out = {}
        for k, val in items:
            try:
                out[int(k.rsplit("/p", 1)[1])] = val
            except (IndexError, ValueError):
                pass
        return out

    def quiesce_mark(self, watermark):
        self.client.key_value_set(
            "bolt/quiesce/e%d/w%d" % (self.epoch, int(watermark)), "1")

    def quiesce_seen(self, watermark):
        try:
            items = self.client.key_value_dir_get(
                "bolt/quiesce/e%d/" % self.epoch)
        except Exception:             # noqa: BLE001
            return False
        want = "w%d" % int(watermark)
        return any(key.rsplit("/", 1)[1] == want for key, _ in items)

    def sweep_epochs(self, keep_from):
        pass                          # keys are deleted behind each beat

    def sweep_peer(self, pid):
        pass

    def stale_marker_count(self):
        return 0


def _default_transport(epoch):
    """File transport when ``BOLT_POD_HB_DIR`` names a shared dir, else
    the jax.distributed KV store, else ``None`` (no liveness layer)."""
    if _ENV_HB_DIR:
        return FileTransport(_ENV_HB_DIR, epoch=epoch)
    from bolt_tpu import _compat
    client = _compat.distributed_client()
    if client is not None:
        return KVTransport(client, epoch=epoch)
    return None


# ---------------------------------------------------------------------
# the watch
# ---------------------------------------------------------------------

# callbacks survive watch restarts (a server subscribed before a reform
# keeps its subscription after); handles deregister
_CB_LOCK = _lockdep.lock("podwatch.callbacks")
_DEATH_CBS = {}                       # handle -> cb(pid)
_REFORM_CBS = {}                      # handle -> cb()
_REJOIN_CBS = {}                      # handle -> cb(ident)
_CB_SEQ = [0]


class _Watch:
    """One process's liveness state: the beat/scan thread plus every
    peer's last-landed beat."""

    def __init__(self, transport, pid, nproc, interval, timeout):
        self.transport = transport
        self.pid = int(pid)
        self.nproc = int(nproc)
        self.interval = float(interval)
        self.timeout = float(timeout)
        self.lock = _lockdep.lock("podwatch.state")
        self.stop_ev = threading.Event()
        self.seq = 0
        self.started = _clock()
        self.last_seq = {}            # pid -> last seen seq
        self.last_seen = {}           # pid -> clock() of last CHANGE
        self.dead = set()             # latched dead peers
        self.farewelled = set()       # peers that LEFT for a reform:
        #                               silent but not dead (a reforming
        #                               survivor must not be latched by
        #                               a slower peer and reformed
        #                               around — the solo-reform race)
        self.coord_error = None       # non-fatal coordination failure
        self.beat_errors = 0
        self.barrier_counts = {}      # name -> next generation
        self.rejoin_seen = set()      # rejoin idents already fanned out
        self.thread = threading.Thread(
            target=self._run, name="bolt-podwatch-heartbeat", daemon=True)

    # -- the heartbeat/scan loop --------------------------------------

    def _run(self):
        fail_since = None
        while not self.stop_ev.is_set():
            try:
                _chaos.hit("podwatch.heartbeat")
                self.seq += 1
                self.transport.beat(self.pid, self.seq)
                self.farewelled |= self.transport.read_farewells()
                self._scan(self.transport.read())
                self._scan_rejoins()
                fail_since = None
            except Exception as exc:  # noqa: BLE001 — a failing beat IS
                now = _clock()        # a signal, never a crash: peers
                with self.lock:       # see our staleness...
                    self.beat_errors += 1
                    if fail_since is None:
                        fail_since = now
                    elif now - fail_since > self.timeout \
                            and self.coord_error is None:
                        # ...and a transport failing for a WHOLE
                        # deadline is itself a liveness verdict: the
                        # store (the coordinator's KV service, the
                        # shared dir) is gone, so guarded syncs must
                        # raise instead of polling a silent watch
                        # forever — the coordinator-death case under
                        # the default KV transport
                        self.coord_error = (
                            "liveness transport failing for %.1fs: %s"
                            % (now - fail_since,
                               str(exc).splitlines()[0][:200]))
            self.stop_ev.wait(self.interval)

    def _scan(self, seqs, now=None):
        now = _clock() if now is None else now
        newly = []
        with self.lock:
            for pid, seq in seqs.items():
                if seq != self.last_seq.get(pid):
                    self.last_seq[pid] = seq
                    self.last_seen[pid] = now
            for pid in range(self.nproc):
                if pid == self.pid or pid in self.dead \
                        or pid in self.farewelled:
                    continue
                seen = self.last_seen.get(pid)
                ref = seen if seen is not None else self.started
                # a peer never seen gets the same staleness budget from
                # the watch's own start — a slow joiner is not dead
                if now - ref > self.timeout:
                    self.dead.add(pid)
                    newly.append(pid)
        for pid in newly:
            _obs.event("podwatch.peer_lost", peer=pid)
            _fire_death(pid)

    def _scan_rejoins(self):
        """Fan newly-announced rejoiners out to :func:`on_rejoin`
        subscribers, once per identity per watch instance."""
        read = getattr(self.transport, "read_rejoin_marks", None)
        if read is None:
            return
        marks = read()
        with self.lock:
            new = marks - self.rejoin_seen
            self.rejoin_seen |= new
        for ident in sorted(new):
            _obs.event("podwatch.rejoin", ident=ident)
            _fire_rejoin(ident)

    # -- queries -------------------------------------------------------

    def peers(self):
        now = _clock()
        out = {}
        with self.lock:
            for pid in range(self.nproc):
                seen = self.last_seen.get(pid)
                out[pid] = {
                    "alive": pid not in self.dead,
                    "self": pid == self.pid,
                    "age": (0.0 if pid == self.pid
                            else now - (seen if seen is not None
                                        else self.started)),
                }
        return out

    def dead_peers(self):
        with self.lock:
            return tuple(sorted(self.dead))

    def mark_dead(self, pid):
        """Latch ``pid`` dead from an out-of-band signal (a
        coordination-service error naming the task, a test)."""
        with self.lock:
            if pid in self.dead or pid == self.pid:
                return
            self.dead.add(pid)
        _obs.event("podwatch.peer_lost", peer=pid)
        _fire_death(pid)


_WATCH = None
_WATCH_LOCK = _lockdep.lock("podwatch.watch")
_EPOCH = [0]


def _default_interval(timeout):
    """The heartbeat cadence a ``timeout`` implies (>= ~4 beats must go
    missing before a verdict) — ONE derivation for :func:`start` and
    :func:`config`, so the checker's rendered recovery plan can never
    drift from the cadence the watch actually runs."""
    if _ENV_INTERVAL:
        return float(_ENV_INTERVAL)
    return min(max(timeout / 5.0, 0.05), 1.0)


def start(nproc, pid, transport=None, dir=None, interval=None,
          timeout=None, epoch=None):
    """Start (or restart) this process's liveness watch for an
    ``nproc``-process pod.  ``multihost.initialize`` calls this on
    every multi-process bring-up; tests call it directly with an
    explicit ``dir`` (file transport) and tight ``interval``/
    ``timeout``.  ``epoch`` PINS the transport epoch instead of
    bumping the local counter — the reform plan carries it, so a
    REJOINED process (whose local counter restarted at zero) lands on
    the same epoch as the incumbents.  Returns True when a watch is
    running (False when no transport exists or the watchdog is
    disabled)."""
    global _WATCH
    timeout = _DEF_TIMEOUT if timeout is None else float(timeout)
    if timeout <= 0 or int(nproc) <= 1:
        return False
    stop()
    with _WATCH_LOCK:
        if epoch is not None:
            _EPOCH[0] = int(epoch)
        else:
            _EPOCH[0] += 1
        epoch = _EPOCH[0]
        if transport is None:
            transport = (FileTransport(dir, epoch=epoch)
                         if dir is not None else _default_transport(epoch))
        if transport is None:
            return False
        if interval is None:
            interval = _default_interval(timeout)
        _WATCH = _Watch(transport, pid, nproc, interval, timeout)
        _WATCH.thread.start()
        return True


def stop(farewell=False):
    """Stop the watch (no-op when none runs).  Callbacks stay
    registered — a restarted watch (reform) keeps its subscribers.

    ``farewell=True`` (the reform path) first publishes a FAREWELL
    marker: this process is leaving the epoch deliberately, so a
    slower peer must keep treating its silence as ALIVE — without it,
    the first survivor to reform goes heartbeat-silent and the second
    falsely latches it dead, computes a solo survivor set, and both
    register as process 0 of the new cluster (the observed
    "newer incarnation" registration collision)."""
    global _WATCH
    with _WATCH_LOCK:
        w, _WATCH = _WATCH, None
    if w is not None:
        if farewell:
            try:
                w.transport.farewell(w.pid)
            except Exception:         # noqa: BLE001 — best effort; the
                pass                  # peer then risks the latch race
        w.stop_ev.set()
        w.thread.join(timeout=5.0)


def active():
    """Is a liveness watch running?"""
    return _WATCH is not None


def epoch():
    """The current transport epoch (the running watch's, else the
    local counter's last value — what the next default ``start`` would
    follow)."""
    w = _WATCH
    return w.transport.epoch if w is not None else _EPOCH[0]


def transport():
    """The running watch's transport, or ``None`` (the supervisor's
    plan/rejoin channel rides it while the watch is up)."""
    w = _WATCH
    return w.transport if w is not None else None


def deadline():
    """The active watchdog deadline in seconds, or ``None`` (watch not
    running — the guards are no-ops)."""
    w = _WATCH
    return w.timeout if w is not None else None


def interval():
    """The active heartbeat interval in seconds, or ``None``."""
    w = _WATCH
    return w.interval if w is not None else None


def config():
    """The watchdog configuration the CHECKER reports (BLT013's
    recovery plan): the live watch's values when running, else the
    process defaults the next ``start`` would use."""
    w = _WATCH
    if w is not None:
        return {"timeout": w.timeout, "interval": w.interval,
                "transport": w.transport.kind, "nproc": w.nproc}
    tout = _DEF_TIMEOUT
    return {"timeout": tout if tout > 0 else None,
            "interval": _default_interval(tout) if tout > 0 else None,
            "transport": "file" if _ENV_HB_DIR else "kv",
            "nproc": None}


def peers():
    """``{pid: {"alive", "self", "age"}}`` for every pod process (empty
    when no watch runs)."""
    w = _WATCH
    return w.peers() if w is not None else {}


def dead_peers():
    """Latched dead process indices (empty tuple when no watch runs)."""
    w = _WATCH
    return w.dead_peers() if w is not None else ()


def alive_peers():
    """Process indices still alive (this one included); empty tuple
    when no watch runs."""
    w = _WATCH
    if w is None:
        return ()
    ps = w.peers()
    return tuple(sorted(p for p, st in ps.items() if st["alive"]))


def mark_dead(pid):
    """Latch ``pid`` dead out-of-band (tests; coordination errors that
    name the task)."""
    w = _WATCH
    if w is not None:
        w.mark_dead(int(pid))


def coordination_error(status):
    """Out-of-band coordination-failure latch: a coordination-service
    error lands here as a liveness verdict — the task index is parsed
    out of the status when present (``.../task:2``) and latched dead,
    otherwise the error text latches as ``coord_error`` (``check()``
    raises on it).  ``multihost`` offers it to
    ``_compat.distributed_initialize`` as the non-fatal client
    callback, but THIS jaxlib cannot install Python callbacks (the
    bridge aborts on invocation — see ``_compat``), so today it fires
    only from tests and future runtimes; live detection rides the
    heartbeat scan and the transport-failure latch instead."""
    text = str(status)
    w = _WATCH
    if w is not None:
        with w.lock:
            w.coord_error = text
    _obs.event("podwatch.coordination_error")
    marker = "task:"
    idx = text.find(marker)
    if idx >= 0:
        digits = ""
        for ch in text[idx + len(marker):]:
            if ch.isdigit():
                digits += ch
            else:
                break
        if digits:
            mark_dead(int(digits))


# -- callbacks ---------------------------------------------------------

def on_peer_death(cb):
    """Register ``cb(pid)`` to fire (from the watch thread) once per
    newly-dead peer.  Returns a handle for :func:`remove_callback`.
    Registrations survive watch restarts (reform)."""
    with _CB_LOCK:
        _CB_SEQ[0] += 1
        h = ("death", _CB_SEQ[0])
        _DEATH_CBS[h] = cb
        return h


def on_reform(cb):
    """Register ``cb()`` to fire after ``multihost.reform`` rebuilds
    the runtime on the survivors (:func:`notify_reform`).  Returns a
    handle for :func:`remove_callback`."""
    with _CB_LOCK:
        _CB_SEQ[0] += 1
        h = ("reform", _CB_SEQ[0])
        _REFORM_CBS[h] = cb
        return h


def on_rejoin(cb):
    """Register ``cb(ident)`` to fire (from the watch thread) once per
    newly-announced rejoiner (:func:`rejoin` markers on the
    transport).  The supervisor subscribes here to drive the
    reform-UP.  Returns a handle for :func:`remove_callback`."""
    with _CB_LOCK:
        _CB_SEQ[0] += 1
        h = ("rejoin", _CB_SEQ[0])
        _REJOIN_CBS[h] = cb
        return h


def remove_callback(handle):
    with _CB_LOCK:
        _DEATH_CBS.pop(handle, None)
        _REFORM_CBS.pop(handle, None)
        _REJOIN_CBS.pop(handle, None)


def _fire_death(pid):
    with _CB_LOCK:
        cbs = list(_DEATH_CBS.values())
    for cb in cbs:
        try:
            cb(pid)
        except Exception:             # noqa: BLE001 — one subscriber's
            pass                      # bug must not mute the rest


def _fire_rejoin(ident):
    with _CB_LOCK:
        cbs = list(_REJOIN_CBS.values())
    for cb in cbs:
        try:
            cb(ident)
        except Exception:             # noqa: BLE001
            pass


def notify_reform():
    """Fan the reform event out to :func:`on_reform` subscribers —
    called by ``multihost.reform`` once the shrunk runtime is up (and
    by tests simulating one)."""
    _obs.event("podwatch.reform")
    with _CB_LOCK:
        cbs = list(_REFORM_CBS.values())
    for cb in cbs:
        try:
            cb()
        except Exception:             # noqa: BLE001
            pass


def rejoin_reset(ident):
    """Forget a consumed-or-deferred rejoin announcement on the
    RUNNING watch: clear the doorbell marker and the scan's
    once-per-identity latch, so the identity's next :func:`rejoin`
    rings through again.  A successful growth reform restarts the
    watch (fresh latch) — this is for the path that did NOT reform,
    e.g. a growth deferred because the pod never went idle."""
    w = _WATCH
    if w is None:
        return
    ident = _safe_ident(ident)
    with w.lock:
        w.rejoin_seen.discard(ident)
    try:
        w.transport.rejoin_clear(ident)
    except Exception:                 # noqa: BLE001 — marker hygiene
        pass


def rejoin(ident, dir=None):
    """Announce this (restarted or replacement) process to a running
    pod: write an epoch-agnostic REJOIN marker the incumbents' watch
    scan picks up (:func:`on_rejoin`).  ``dir`` names the shared
    transport directory (default ``BOLT_POD_HB_DIR``); with a watch
    already running its transport is used instead.  The full join
    dance (wait for the plan, reform in) is
    ``parallel.supervisor.attach`` — this is just the doorbell."""
    w = _WATCH
    tr = w.transport if w is not None else None
    if tr is None:
        path = dir if dir is not None else _ENV_HB_DIR
        if not path:
            raise RuntimeError(
                "podwatch.rejoin needs a shared transport: pass dir= "
                "or set BOLT_POD_HB_DIR (re-expansion needs a "
                "rendezvous medium that outlives the dead peer)")
        tr = FileTransport(path, epoch=0)
    tr.rejoin_mark(ident)
    _obs.event("podwatch.rejoin_announce", ident=str(ident))
    return tr


def sweep_stale_markers():
    """Transport hygiene after a reform: drop heartbeat/farewell/
    barrier/quiesce markers from epochs older than the previous one
    and reform plans more than two generations stale — the shared dir
    must not grow without bound across repeated reforms (ISSUE 12
    satellite).  No-op without a watch."""
    w = _WATCH
    if w is not None:
        try:
            w.transport.sweep_epochs(w.transport.epoch - 1)
        except Exception:             # noqa: BLE001 — hygiene is
            pass                      # best-effort, never a crash


def sweep_dead_markers():
    """Drop latched-DEAD peers' heartbeat markers (called by
    ``checkpoint.stream_clear`` alongside its dead-shard sweep).
    No-op without a watch or dead peers."""
    w = _WATCH
    if w is None:
        return
    for pid in w.dead_peers():
        try:
            w.transport.sweep_peer(pid)
        except Exception:             # noqa: BLE001
            pass


# ---------------------------------------------------------------------
# pod-run accounting + the quiesce latch (the supervisor's seams)
# ---------------------------------------------------------------------

_BUSY_LOCK = _lockdep.lock("podwatch.busy")
_BUSY = [0]                           # live pod stream runs, this process
_QUIESCE = [None]                     # reason string while requested


def pod_enter():
    """A pod stream run started (the executor's accounting — the
    supervisor must not reform UP while a healthy collective schedule
    is in flight)."""
    with _BUSY_LOCK:
        _BUSY[0] += 1


def pod_exit():
    with _BUSY_LOCK:
        _BUSY[0] = max(0, _BUSY[0] - 1)


def pod_busy():
    """Live pod stream runs on this process."""
    with _BUSY_LOCK:
        return _BUSY[0]


def request_quiesce(reason="rejoin"):
    """Ask in-flight pod streams to stop at their next slab-boundary
    checkpoint (:func:`quiesce_gate`) so the pod can reform to a
    larger topology.  Idempotent; cleared by :func:`clear_quiesce`."""
    _QUIESCE[0] = str(reason)
    _obs.event("podwatch.quiesce_requested", reason=str(reason))


def clear_quiesce():
    _QUIESCE[0] = None


def quiesce_requested():
    """The active quiesce reason, or ``None``."""
    return _QUIESCE[0]


def quiesce_pre(watermark):
    """Process 0's half of the quiesce decision, taken right BEFORE a
    pod stream's periodic checkpoint at ``watermark``: publish the
    watermark-named marker now, so the rendezvous the checkpoint
    itself performs (shard barrier, then meta barrier) fences its
    visibility — :func:`quiesce_gate` with ``fenced=True`` then needs
    no second standalone barrier per checkpoint.  No-op without a
    watch and on non-zero ranks."""
    w = _WATCH
    if w is not None and w.pid == 0 and _QUIESCE[0] is not None:
        w.transport.quiesce_mark(watermark)


def quiesce_gate(watermark, fenced=False):
    """The slab-boundary quiesce decision, taken right AFTER a pod
    stream's periodic checkpoint at ``watermark`` retired slabs.

    Process 0 is the single decider: if ITS quiesce latch is set it
    publishes a watermark-named marker through the transport; a
    barrier then fences the read, so every process sees the same
    answer at the same watermark and raises the same
    :class:`PodQuiesceError` — nobody dispatches a collective the
    others have abandoned.  With ``fenced=True`` the caller already
    fenced the marker through the checkpoint's own rendezvous
    (:func:`quiesce_pre` before ``stream_save``'s two barriers), so
    the standalone barrier is skipped — the common per-checkpoint
    path pays ZERO extra cross-process syncs for the gate.  A latch
    set on a non-zero process trips at the next gate after process
    0's own watch scans the rejoin marker (one heartbeat interval
    behind, at most).  No-op without a watch."""
    w = _WATCH
    if w is None:
        return
    if not fenced:
        if w.pid == 0 and _QUIESCE[0] is not None:
            w.transport.quiesce_mark(watermark)
        barrier("bolt_quiesce_gate")
    if w.transport.quiesce_seen(watermark):
        if _QUIESCE[0] is None:
            # process 0 decided before THIS process's own watch scanned
            # the rejoin marker: latch locally NOW, so the serving
            # layer holds the retry instead of re-running into a pod
            # whose peers are already tearing down for the reform (they
            # farewelled — silent-but-alive — so the re-run's collective
            # would hang, not fail)
            _QUIESCE[0] = "peer quiesce at %d retired slabs" \
                % int(watermark)
        raise PodQuiesceError(
            "pod quiesce at %d retired slabs (%s): this streamed run "
            "stopped at its slab-boundary checkpoint so the pod can "
            "reform to the larger topology; re-run to resume from the "
            "checkpoint — bit-identically, on the re-expanded pod"
            % (int(watermark), _QUIESCE[0] or "supervisor"),
            slab=int(watermark), phase="quiesce gate")


def ready_rendezvous(name="bolt_stream_ready"):
    """Pre-collective readiness rendezvous (ISSUE 12): every pod
    process confirms liveness over the heartbeat transport RIGHT
    BEFORE its first collective dispatch of a run.  A peer that died
    before dispatching never arrives and the watchdog barrier raises
    the pointed :class:`PeerLostError` within ~2x ``BOLT_POD_TIMEOUT``
    — instead of the survivor blocking ~30s in gloo's connect (the
    documented pre-PR-12 bound; a peer dying in the microseconds
    between passing this rendezvous and dispatching still pays the
    transport timeout, now the only residual window).  No-op without
    a watch (``BOLT_POD_TIMEOUT=0`` keeps the old bound)."""
    if _WATCH is None:
        return False
    barrier(name)
    return True


# ---------------------------------------------------------------------
# the collective watchdog
# ---------------------------------------------------------------------

def check(phase=None, slab=None):
    """Raise :class:`PeerLostError` if the watch has latched a dead
    peer (no-op otherwise, and when no watch runs)."""
    w = _WATCH
    if w is None:
        return
    dead = w.dead_peers()
    if dead:
        raise PeerLostError(_lost_message(dead, phase, slab),
                            peer=dead[0], slab=slab, phase=phase)
    with w.lock:
        err = w.coord_error
    if err is not None:
        raise PeerLostError(
            _lost_message((), phase, slab)
            + " [coordination service: %s]" % err.splitlines()[0][:200],
            slab=slab, phase=phase)


def wait_ready(value, phase="collective", slab=None, poll=None):
    """Watchdog-guarded readiness wait: poll every jax-array leaf of
    ``value`` for ``is_ready()`` instead of blocking in the runtime, so
    a collective hung on a dead peer raises the pointed
    :class:`PeerLostError` (naming the peer and the in-flight slab)
    instead of hanging this survivor forever.

    Returns once every leaf is ready (an ERRORED buffer reads ready
    too — the caller's actual ``block_until_ready`` then surfaces the
    transport error, which :func:`reraise` classifies).  With no watch
    running this returns immediately (the caller blocks normally)."""
    w = _WATCH
    if w is None:
        return
    import jax
    leaves = [x for x in jax.tree_util.tree_leaves(value)
              if callable(getattr(x, "is_ready", None))]
    if not leaves:
        return
    poll = min(w.interval, 0.02) if poll is None else poll
    while True:
        pending = []
        for leaf in leaves:
            try:
                if not leaf.is_ready():
                    pending.append(leaf)
            except Exception:         # noqa: BLE001 — an errored buffer
                pass                  # is "ready": the block raises it
        if not pending:
            return
        leaves = pending
        check(phase=phase, slab=slab)
        time.sleep(poll)


def reraise(exc, phase="collective", slab=None, wait=True):
    """Classify a failure from a pod collective: a transport-signature
    error (gloo connection closed, coordination RPC refused — the FAST
    shape of peer death) or a latched dead peer raises
    :class:`PeerLostError` chained to ``exc``; anything else re-raises
    ``exc`` untouched.  ``wait=True`` gives the liveness layer up to
    one watchdog deadline to NAME the dead peer (the transport error
    usually lands milliseconds after the kill, the heartbeat verdict
    one timeout later)."""
    if isinstance(exc, PeerLostError):
        raise exc
    w = _WATCH
    dead = dead_peers()
    transport = is_transport_error(exc)
    secondary = is_secondary_sign(exc)
    if not dead and not transport and not secondary:
        raise exc
    if not dead and w is not None and wait:
        deadline_t = _clock() + w.timeout + 2 * w.interval
        while not dead and _clock() < deadline_t:
            time.sleep(min(w.interval, 0.05))
            dead = dead_peers()
    if not dead and not transport:
        # a secondary sign with nobody actually dead is NOT peer loss —
        # surface the genuine deleted-array bug untouched
        raise exc
    raise PeerLostError(
        _lost_message(dead, phase, slab),
        peer=dead[0] if dead else None, slab=slab, phase=phase) from exc


@contextlib.contextmanager
def guard(phase, slab=None):
    """Arm the watchdog around one pod collective dispatch: failures
    inside classify through :func:`reraise` (transport error or dead
    peer → :class:`PeerLostError`); a pre-latched dead peer refuses
    before dispatching into a doomed rendezvous."""
    check(phase=phase, slab=slab)
    try:
        yield
    except PeerLostError:
        raise
    except Exception as exc:          # noqa: BLE001 — classified below
        reraise(exc, phase=phase, slab=slab)


# ---------------------------------------------------------------------
# the watchdog barrier
# ---------------------------------------------------------------------

def barrier(name, timeout=None):
    """Transport-level rendezvous of every live pod process, with the
    watchdog armed: a peer that dies before arriving raises
    :class:`PeerLostError` on every survivor within ~one heartbeat
    timeout (the harness proves < 2x), and a peer that is alive but
    never arrives (code divergence) raises a pointed RuntimeError after
    ``_BARRIER_STALL_X`` deadlines.  Generations are counted PER NAME —
    every process calls barriers in the same deterministic order, so
    repeated names (checkpoint cadences) never collide."""
    w = _WATCH
    if w is None:
        raise RuntimeError(
            "podwatch.barrier needs a running liveness watch "
            "(multihost.initialize starts one on multi-process runs)")
    with w.lock:
        count = w.barrier_counts.get(name, 0)
        w.barrier_counts[name] = count + 1
    name = str(name)
    w.transport.barrier_mark(name, count, w.pid)
    stall = (timeout if timeout is not None
             else max(w.timeout * _BARRIER_STALL_X, 30.0))
    t0 = _clock()
    want = set(range(w.nproc))
    while True:
        try:
            seen = w.transport.barrier_seen(name, count)
        except Exception as exc:      # noqa: BLE001 — a dead store is a
            reraise(exc, phase="barrier %r" % name)   # peer-loss signal
        dead = set(w.dead_peers())
        if dead:
            # the rendezvous is doomed: every survivor sees the same
            # dead set and fails the SAME barrier deterministically
            raise PeerLostError(
                _lost_message(sorted(dead), "barrier %r" % name, None),
                peer=sorted(dead)[0], phase="barrier %r" % name)
        if want <= seen:
            w.transport.barrier_sweep(name, count, w.pid)
            return
        if _clock() - t0 > stall:
            raise RuntimeError(
                "podwatch.barrier %r stalled: processes %s never "
                "arrived within %.1fs yet their heartbeats are live — "
                "the pod's processes have diverged (different barrier "
                "order?)" % (name, sorted(want - seen - dead), stall))
        time.sleep(min(w.interval, 0.05))
