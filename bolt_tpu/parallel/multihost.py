"""Multi-process (pod-scale) execution: the ``jax.distributed``
bootstrap and the per-process topology/ingest helpers.

Everything below this module keeps the single-controller programming
model — one global mesh, one logical array, collectives inserted from
sharding specs — but a POD is many OS processes, each owning a slice of
the devices.  This module is the ONE place that knows about that
(lint rule BLT110: ``jax.distributed`` / ``jax.process_index`` /
``jax.process_count`` live here and in ``_compat.py`` only):

* :func:`initialize` / :func:`shutdown` — bring up (and tear down) the
  distributed runtime.  On CPU backends the cross-process collective
  transport (gloo) is armed first; without it a multi-process program
  fails at dispatch with XLA's "Multiprocess computations aren't
  implemented on the CPU backend" — exactly what the localhost test
  clusters would otherwise hit.
* :func:`process_index` / :func:`process_count` /
  :func:`is_multiprocess` / :func:`mesh_process_count` — topology
  queries every other module routes through here.
* :func:`local_slab_spec` — the per-process INGEST contract of the
  streaming executor (``bolt_tpu.stream``): for a global slab of
  records, which contiguous sub-range of the leading key axis THIS
  process produces and uploads.  Each host touches only its own shard
  of each slab; the global ``jax.Array`` is assembled from the local
  parts (``jax.make_array_from_single_device_arrays``) with no
  cross-host data motion at ingest time.
* :func:`slab_divisibility_error` — the BLT012 rule: every slab's
  record extent must divide the key-axis device assignment, or the
  per-process split does not exist (the analysis checker emits the
  same message as a ``BLT012`` diagnostic; the executor refuses with
  it before any thread starts).
* :func:`barrier` — a named cross-process rendezvous.  With the
  liveness watch running (``bolt_tpu.parallel.podwatch``) it is the
  WATCHDOG barrier: a transport-level rendezvous that converts a dead
  peer into a pointed :class:`podwatch.PeerLostError` instead of
  blocking in a dead collective; otherwise it is
  ``multihost_utils.sync_global_devices`` taken under the engine's
  dispatch-order lock, so a barrier collective can never interleave
  with another thread's program enqueue inside one process.
* :func:`local_value` — the host view of a replicated global array
  (``np.asarray`` refuses non-fully-addressable arrays; every process
  holds a full copy of a ``P()``-replicated value in its own shards).
* :func:`reform` — the SHRINK-AND-RESUME door (ISSUE 11): after a peer
  death, the survivors tear the runtime down (without the stock
  shutdown's fatal barrier), rebuild it as an M<N-process cluster on a
  fresh coordinator, and notify ``podwatch.on_reform`` subscribers —
  a checkpointed stream then resumes on the smaller pod from the last
  rendezvous-consistent watermark.

The bring-up is SURVIVABLE (``_compat.distributed_initialize``): the
stock client ``LOG(QFATAL)``'s every survivor the moment one peer dies
— the exact outage this layer exists to handle — so the coordination
service's own failure detection is made unreachable (wide heartbeat
tolerance + ``shutdown_on_destruction=False``; this jaxlib's Python
error-callback bridge aborts on invocation, so no callback can be
installed).  Peer-death DETECTION therefore belongs entirely to
``podwatch``: its own heartbeats, the transport-failure latch, and
the gloo transport-error signatures.
"""

import hashlib
import json
import time

import numpy as np

import jax

from bolt_tpu import _chaos
from bolt_tpu import _compat
from bolt_tpu.parallel import podwatch
from bolt_tpu.parallel.podwatch import PeerLostError  # noqa: F401 — the
#   blessed re-export: callers catch multihost.PeerLostError

# ---------------------------------------------------------------------
# bootstrap / teardown
# ---------------------------------------------------------------------

_INITIALIZED = False


def initialize(coordinator_address=None, num_processes=None,
               process_id=None):
    """Bootstrap the multi-process runtime (DCN / localhost cluster).

    ::

        multihost.initialize("10.0.0.1:8476", num_processes=4,
                             process_id=rank)

    Call BEFORE any backend query (device listing, array construction).
    On CPU the gloo collective transport is configured first — the
    2-process localhost test clusters run real cross-process programs
    through it.  The client is brought up SURVIVABLE where the runtime
    allows (`_compat.distributed_initialize`): a dead peer becomes a
    ``podwatch`` event, not a process abort — and the per-process
    liveness watch starts automatically on every multi-process
    bring-up (disable with ``BOLT_POD_TIMEOUT=0``).  Idempotent:
    returns ``True`` when this call initialised the runtime, ``False``
    when it was already up (or the runtime declined — a plain
    single-process run)."""
    global _INITIALIZED
    if _INITIALIZED:
        return False
    try:
        # without a cross-process collective implementation the CPU
        # backend compiles single-process only; flag spelling is
        # version-sensitive, so probe quietly
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass
    try:
        if None in (coordinator_address, num_processes, process_id):
            # auto-detection (or the plain single-process decline) is
            # the stock path's job
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id)
        else:
            _compat.distributed_initialize(
                coordinator_address, int(num_processes), int(process_id),
                on_fatal=podwatch.coordination_error)
    except (RuntimeError, ValueError):
        # already initialised elsewhere, or a single-process run
        return False
    _INITIALIZED = True
    if int(num_processes or jax.process_count()) > 1:
        podwatch.start(int(num_processes or jax.process_count()),
                       int(process_id if process_id is not None
                           else jax.process_index()))
    return True


def shutdown():
    """Tear down a runtime :func:`initialize` brought up (no-op
    otherwise — a runtime initialised elsewhere is not ours to stop).
    The teardown is graceful (shutdown barrier) only while every peer
    is alive; next to a dead peer the handles are simply dropped — the
    stock barrier would abort the process."""
    global _INITIALIZED
    if not _INITIALIZED:
        return False
    graceful = not podwatch.dead_peers()
    # farewell: a deliberately-exiting process goes heartbeat-silent
    # while it waits in the coordination shutdown barrier — without the
    # marker a peer still streaming past BOLT_POD_TIMEOUT would latch
    # this clean leaver DEAD and poison its own healthy run
    podwatch.stop(farewell=True)
    try:
        _compat.distributed_teardown(graceful=graceful)
    except (RuntimeError, ValueError):
        pass
    _INITIALIZED = False
    return True


def is_initialized():
    """Did :func:`initialize` bring up the distributed runtime?"""
    return _INITIALIZED


def reform(coordinator_address, num_processes, process_id=None,
           epoch=None, init_timeout=None):
    """Shrink-OR-GROW-and-resume (ISSUEs 11/12): rebuild the
    distributed runtime as a ``num_processes``-wide cluster — on the
    SURVIVORS of a peer death, or on survivors PLUS rejoined
    replacements (the re-expansion door ``parallel.supervisor``
    drives).

    ::

        try:
            big.sum().cache()              # 3-process pod, peer dies
        except multihost.PeerLostError:
            multihost.reform("10.0.0.1:8477", num_processes=2)
            ...rebuild mesh from jax.devices(), re-run the pipeline...

    (Manual form; ``serve.Server(supervise=True)`` automates the whole
    dance.)  Every member calls this with the SAME fresh coordinator
    address; ``process_id`` defaults to this process's rank among the
    surviving old indices (the liveness watch's view — survivors all
    compute the same mapping).  The old client/service are dropped
    WITHOUT the shutdown barrier (it would fail against the dead
    task), every XLA backend and jit cache is cleared
    (``_compat.clear_backends`` — the new backend must see the new
    topology), the engine's executable cache is dropped (old entries
    pin programs compiled against dead backends), and the liveness
    watch restarts for the new epoch — ``epoch`` PINS it (the
    supervisor's plan carries the value, so a REJOINED process whose
    local counter restarted lands on the incumbents' epoch).
    ``init_timeout`` bounds the bring-up wait (the supervisor passes a
    short one so a second death mid-reform fails the attempt fast).
    Stale transport markers from epochs before the previous one are
    swept after the watch restarts (``BOLT_POD_HB_DIR`` must not grow
    without bound across repeated reforms).  ``podwatch.on_reform``
    subscribers (the serving layer's admission drain) are notified
    last.  Works for a FRESH process too (the rejoiner: nothing to
    tear down).  Returns the new process id."""
    global _INITIALIZED
    if process_id is None:
        alive = podwatch.alive_peers()
        if not alive:
            raise RuntimeError(
                "multihost.reform needs process_id= when no liveness "
                "watch is running (the survivors' rank mapping comes "
                "from podwatch.alive_peers)")
        old_pid = process_index()
        if old_pid not in alive:
            raise RuntimeError(
                "multihost.reform: this process (%d) is not among the "
                "surviving peers %s" % (old_pid, list(alive)))
        process_id = alive.index(old_pid)
    if int(num_processes) < 1:
        raise ValueError("reform num_processes must be >= 1, got %r"
                         % (num_processes,))
    try:
        # a FRESH process joining through the rejoin door never ran
        # initialize(), so the CPU cross-process collective transport
        # must be armed here too (idempotent for survivors)
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass
    podwatch.stop(farewell=True)
    # backends first: the gloo-backed CPU client references the
    # coordination client, and that reference must drop BEFORE the
    # client handle goes (its destructor joins the error-poll thread —
    # see _compat.distributed_teardown's ordering contract)
    _compat.clear_backends()
    _compat.distributed_teardown(graceful=False)
    from bolt_tpu import engine as _engine
    _engine.clear()
    kw = {} if init_timeout is None else {"init_timeout":
                                          int(init_timeout)}
    _compat.distributed_initialize(
        coordinator_address, int(num_processes), int(process_id),
        on_fatal=podwatch.coordination_error, **kw)
    _INITIALIZED = True
    if int(num_processes) > 1:
        podwatch.start(int(num_processes), int(process_id), epoch=epoch)
        podwatch.sweep_stale_markers()
    podwatch.notify_reform()
    return int(process_id)


def heal_backend_init():
    """Recover from a POISONED CPU-backend bring-up on the live
    coordination service.

    The distributed CPU backend's topology exchange inserts
    ``cpu:local_topology/cpu/<pid>`` into the coordination KV store and
    then waits for every peer's key.  When the exchange FAILS partway —
    a peer died, reformed to a newer plan generation, or simply had not
    retried yet within the 2-minute window — this process's own key
    stays behind, and every later rebuild against the same service dies
    instantly with ``ALREADY_EXISTS`` on its own insert.  Worse, the
    poison is symmetric: a peer in the same state can never re-publish
    either, so each side's exchange waits forever on a key the other
    side is barred from inserting — the wedge is self-sustaining until
    someone deletes the stale keys.

    This helper deletes THIS process's stale topology key (plus the
    best-effort composed global-topology key) and drops the failed
    backend state, so the next backend query re-runs the exchange
    cleanly.  Safe by construction: it only ever runs after a FAILED
    bring-up (no healthy backend exists to invalidate), and each
    process deletes only the key it owns.  Returns ``True`` when a
    live client was found to heal against."""
    client = _compat.distributed_client()
    if client is None:
        return False
    st = _compat._distributed_state()
    pid = int(getattr(st, "process_id", 0) or 0)
    for key in ("cpu:local_topology/cpu/%d" % pid, "cpu:global_topology"):
        try:
            client.key_value_delete(key)
        except Exception:             # noqa: BLE001 — absent key / dead
            pass                      # store: nothing to heal there
    try:
        _compat.clear_backends()
    except Exception:                 # noqa: BLE001 — no reset hook on
        pass                          # this jax: the retry still re-runs
    from bolt_tpu import engine as _engine
    _engine.clear()
    from bolt_tpu.obs import trace as _obs
    _obs.event("multihost.backend_heal", process_id=pid)
    return True


# ---------------------------------------------------------------------
# topology queries (the BLT110 home)
# ---------------------------------------------------------------------

def process_index():
    """This process's index in the cluster (0 single-process)."""
    return jax.process_index()


def process_count():
    """Total processes in the cluster (1 single-process)."""
    return jax.process_count()


def is_multiprocess(mesh=None):
    """Does ``mesh`` (or, with no mesh, the runtime) span more than one
    process?"""
    if mesh is None:
        return process_count() > 1
    return mesh_process_count(mesh) > 1


def mesh_process_count(mesh):
    """Number of DISTINCT processes owning ``mesh``'s devices."""
    if mesh is None:
        return 1
    return len({d.process_index for d in np.asarray(mesh.devices).flat})


def topology_token():
    """Hashable process-topology component for engine program keys:
    multi-process slab programs (shard_map + collectives) must never
    share a cache entry with their single-process twins, and the token
    records the pod width the program was compiled for."""
    n = process_count()
    return ("mh", n) if n > 1 else None


def local_value(x):
    """Host ``np.ndarray`` view of ``x``'s locally-addressable data.

    A ``P()``-replicated global array (every cross-host fold partial the
    streaming executor produces) holds one full copy per device;
    ``np.asarray`` refuses the non-fully-addressable global, so the view
    comes from the first addressable shard.  Fully-addressable arrays
    (and plain host values) pass straight through ``np.asarray``."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        return np.asarray(x.addressable_shards[0].data)
    return np.asarray(x)


def barrier(name):
    """Named cross-process rendezvous (no-op single-process).

    With the liveness watch running this is the WATCHDOG barrier
    (``podwatch.barrier``): a transport-level rendezvous that raises a
    pointed :class:`PeerLostError` on every survivor when a peer dies
    before arriving — within ~one heartbeat timeout, never an infinite
    hang.  Without a watch it falls back to the device-collective
    rendezvous, taken under the engine's dispatch-order lock: the
    barrier is a collective program, and a second thread enqueueing
    another program mid-barrier would interleave the per-device queues
    — the exact deadlock the order lock exists to prevent."""
    if process_count() <= 1:
        return
    _chaos.hit("multihost.barrier")
    if podwatch.active():
        podwatch.barrier(name)
        return
    from jax.experimental import multihost_utils
    from bolt_tpu import engine as _engine
    with _engine.order_lock():
        # the barrier IS a collective program enqueue: it must hold the
        # order lock for exactly the reason BLT113 flags collectives
        # under locks everywhere else — here the lock serialises this
        # enqueue against every other dispatch, keeping the per-device
        # queues aligned across processes
        multihost_utils.sync_global_devices(str(name))  # lint: allow(BLT113 the barrier is itself an ordered enqueue)


# ---------------------------------------------------------------------
# the dispatch-schedule verifier (the engine digest's rendezvous)
# ---------------------------------------------------------------------

class ScheduleDivergenceError(RuntimeError):
    """The pod's processes enqueued DIFFERENT program schedules — the
    divergence that otherwise surfaces as a silent gloo collective
    hang.  Carries the first divergent position when key logging was
    armed (``BOLT_SCHED_LOG=1``)."""

    def __init__(self, message, peer=None, index=None, local_key=None):
        super().__init__(message)
        self.peer = peer              # the diverging process id
        self.index = index            # first divergent schedule slot
        self.local_key = local_key    # this process's key at that slot


_VERIFY_SEQ = [0]                     # per-process call counter: every
#                                       process calls verify_schedule at
#                                       the same program points (the
#                                       barrier-name discipline), so the
#                                       counter yields matching tags

_NOTE_KEYS = 256                      # per-key hashes shipped at most
_NOTE_CHARS = 160                     # chars of each key text shipped


def _schedule_payload():
    from bolt_tpu import engine as _engine
    count, digest = _engine.schedule_digest()
    payload = {"count": count, "digest": digest}
    log = _engine.schedule_log()
    if log is not None:
        tail = log[-_NOTE_KEYS:]
        payload["base"] = len(log) - len(tail)
        payload["hashes"] = [hashlib.sha256(t.encode()).hexdigest()[:12]
                             for t in tail]
        payload["texts"] = [t[:_NOTE_CHARS] for t in tail]
    return payload


def _first_divergence(mine, theirs):
    """First divergent schedule slot between two payloads carrying key
    logs, or ``None`` when the logs don't overlap usefully."""
    if "hashes" not in mine or "hashes" not in theirs:
        return None
    base = max(mine["base"], theirs["base"])
    a = mine["hashes"][base - mine["base"]:]
    b = theirs["hashes"][base - theirs["base"]:]
    for i, (ha, hb) in enumerate(zip(a, b)):
        if ha != hb:
            return base + i
    if len(a) != len(b):
        return base + min(len(a), len(b))
    return None


def verify_schedule(name="sched", timeout=30.0, transport=None):
    """Cross-process dispatch-order check: exchange this process's
    schedule digest (:func:`bolt_tpu.engine.schedule_digest`) with
    every pod member and FAIL LOUDLY on divergence.

    The engine's order lock guarantees one enqueue order per process;
    nothing guarantees the pods agreed on it — a divergent schedule
    runs mismatched collectives and hangs in gloo with no diagnosis.
    Call this at any quiet point (every process must call it at the
    SAME program point, like a barrier): matching schedules return the
    common digest; a mismatch raises :class:`ScheduleDivergenceError`
    naming the diverging peer — and, when key logging is armed
    (``BOLT_SCHED_LOG=1`` / ``engine.schedule_log_arm()``), the first
    divergent slot and this process's program key in it.

    Single-process: returns the local digest without any exchange."""
    from bolt_tpu import engine as _engine
    payload = _schedule_payload()
    if process_count() <= 1:
        return payload["digest"]
    pid = process_index()
    nproc = process_count()
    if transport is None:
        transport = podwatch.transport() if podwatch.active() \
            else podwatch._default_transport(epoch=podwatch.epoch())
    if transport is None:
        raise RuntimeError(
            "verify_schedule needs a podwatch transport (shared "
            "BOLT_POD_HB_DIR or the jax.distributed KV store)")
    _VERIFY_SEQ[0] += 1
    key = "sched.%s.%d" % (name, _VERIFY_SEQ[0])
    transport.note_set(key, pid, json.dumps(payload))
    deadline = time.monotonic() + timeout
    while True:
        notes = transport.note_read(key)
        if len(notes) >= nproc:
            break
        if time.monotonic() >= deadline:
            raise RuntimeError(
                "verify_schedule %r: only %d/%d processes published a "
                "schedule digest within %.1fs (peers missing: %s)"
                % (key, len(notes), nproc, timeout,
                   sorted(set(range(nproc)) - set(notes))))
        time.sleep(0.02)
    for peer in sorted(notes):
        if peer == pid:
            continue
        theirs = json.loads(notes[peer])
        if theirs["digest"] == payload["digest"]:
            continue
        idx = _first_divergence(payload, theirs)
        local_key = None
        if idx is not None and "texts" in payload:
            off = idx - payload["base"]
            if 0 <= off < len(payload["texts"]):
                local_key = payload["texts"][off]
        detail = "" if idx is None else (
            "; first divergent slot %d, local key %s"
            % (idx, local_key if local_key is not None
               else "<beyond local log>"))
        raise ScheduleDivergenceError(
            "dispatch schedules diverged: process %d enqueued %d "
            "program(s) [digest %s..], process %d enqueued %d [digest "
            "%s..]%s — every process must enqueue the SAME programs in "
            "the SAME order (arm BOLT_SCHED_LOG=1 for exact keys)"
            % (pid, payload["count"], payload["digest"][:12],
               peer, theirs["count"], theirs["digest"][:12], detail),
            peer=peer, index=idx, local_key=local_key)
    return payload["digest"]


# ---------------------------------------------------------------------
# the per-process ingest contract (bolt_tpu.stream)
# ---------------------------------------------------------------------

class LocalSlabSpec:
    """The per-process slab contract for one streamed source geometry:
    which contiguous sub-range of each slab's leading key axis THIS
    process produces and uploads.  Built by :func:`local_slab_spec`;
    consumed by the streaming executor's uploader pool.

    ``local_range(lo, hi)`` maps a global slab ``[lo, hi)`` to the
    process-local ``[llo, lhi)`` in GLOBAL record coordinates — the
    slices a ``fromcallback(..., per_process=True)`` loader is invoked
    with.  Raises the pointed BLT012 error when the slab extent does
    not divide the key-axis device assignment (no per-process split
    exists)."""

    __slots__ = ("mesh", "shape", "split", "pid", "nproc", "_cache")

    def __init__(self, mesh, shape, split):
        self.mesh = mesh
        self.shape = tuple(int(s) for s in shape)
        self.split = int(split)
        self.pid = process_index()
        self.nproc = mesh_process_count(mesh)
        self._cache = {}

    def slab_shape(self, lo, hi):
        return (hi - lo,) + self.shape[1:]

    def local_range(self, lo, hi):
        """Global-coordinate ``[llo, lhi)`` of slab ``[lo, hi)`` this
        process ingests (identity when the mesh is single-process)."""
        llo, lhi = self._local_box(hi - lo)
        return lo + llo, lo + lhi

    def _local_box(self, nrec):
        """Per-slab-length local axis-0 range ``(llo, lhi)`` RELATIVE to
        the slab, derived from the key sharding's addressable-device
        index map — contiguity and coverage are verified, so a mesh
        whose process boundary does not fall on the leading key axis is
        refused instead of silently mis-ingested."""
        got = self._cache.get(nrec)
        if got is not None:
            return got
        if self.nproc <= 1:
            out = (0, nrec)
            self._cache[nrec] = out
            return out
        err = slab_divisibility_error(self.mesh, self.shape, self.split,
                                      [(0, nrec)])
        if err is not None:
            raise ValueError(err)
        from bolt_tpu.parallel.sharding import key_sharding
        shape = (nrec,) + self.shape[1:]
        sharding = key_sharding(self.mesh, shape, self.split)
        items = sharding.addressable_devices_indices_map(shape)
        # DEDUPED boxes: a mesh axis that does not shard the slab
        # replicates it, so several local devices hold the SAME region
        # — replicas are a placement detail, not coverage (the same
        # dedup _materialize_base and _gather_multihost apply)
        boxes = {tuple(s.indices(n)[:2] for s, n in zip(idx, shape))
                 for idx in items.values()}
        llo = min(b[0][0] for b in boxes)
        lhi = max(b[0][1] for b in boxes)
        vol = sum(int(np.prod([hi0 - lo0 for lo0, hi0 in b]))
                  for b in boxes)
        want = (lhi - llo) * int(np.prod(self.shape[1:], dtype=np.int64)) \
            if len(self.shape) > 1 else (lhi - llo)
        if vol != want:
            raise ValueError(
                "multi-process streaming needs the process boundary on "
                "the leading key axis: this mesh scatters process %d's "
                "devices across a non-contiguous region of a %d-record "
                "slab; use a mesh whose leading axis spans the "
                "processes in order" % (self.pid, nrec))
        out = (llo, lhi)
        self._cache[nrec] = out
        return out


def local_slab_spec(mesh, shape=None, split=None):
    """The :class:`LocalSlabSpec` for one streamed geometry.  Accepts
    either ``(mesh, shape, split)`` or a single source-like object with
    ``.mesh`` / ``.shape`` / ``.split`` attributes (a
    ``stream.StreamSource``)."""
    if shape is None and hasattr(mesh, "mesh"):
        src = mesh
        return LocalSlabSpec(src.mesh, src.shape, src.split)
    return LocalSlabSpec(mesh, shape, split)


def key_collective_axes(mesh, shape, split):
    """Mesh-axis names the leading key axes shard over — the axes the
    multi-process slab program's cross-host fold reduces with
    (``psum``/``pmin``/``pmax``)."""
    from bolt_tpu.parallel.sharding import key_spec, spec_names
    spec = key_spec(mesh, shape, split)
    return tuple(n for e in tuple(spec)[:split] for n in spec_names(e))


def slab_divisibility_error(mesh, shape, split, ranges):
    """The BLT012 rule, as one shared message (``analysis.check`` emits
    it as a diagnostic; the streaming executor raises it): every slab's
    leading extent must keep the SAME key-axis device assignment the
    full shape has, or per-process sub-slabs do not exist for that slab
    and the cross-host fold would silently double-count replicated
    records.  Returns the message string, or ``None`` when every slab
    in ``ranges`` divides."""
    if mesh_process_count(mesh) <= 1:
        return None
    full_axes = key_collective_axes(mesh, shape, split)
    if not full_axes:
        width = int(np.prod([mesh.shape[n] for n in mesh.axis_names
                             if mesh.shape[n] > 1], dtype=np.int64))
        return ("BLT012: key axes %s do not divide the %d-device "
                "multi-process mesh %s, so no per-process shard "
                "assignment exists; choose key extents divisible by "
                "the mesh axis sizes"
                % (tuple(shape[:split]), width, dict(mesh.shape)))
    for lo, hi in ranges:
        slab_shape = (hi - lo,) + tuple(shape[1:])
        axes = key_collective_axes(mesh, slab_shape, split)
        if axes != full_axes:
            width = int(np.prod([mesh.shape[n] for n in full_axes],
                                dtype=np.int64))
            return ("BLT012: slab [%d, %d) holds %d records, not "
                    "divisible by the %d-way key-axis device assignment "
                    "%s — the per-process ingest split does not exist "
                    "for it; pick chunks= (records per slab) and a key "
                    "extent that are multiples of %d, or pad the "
                    "source (uneven tails cannot stream on a "
                    "multi-process mesh)"
                    % (lo, hi, hi - lo, width, full_axes, width))
    return None


def sidecar_codec_error(codec, mesh):
    """The pod-scale codec rule, as one shared message (``stream``
    raises it; ``analysis.check`` forecasts it under BLT016): a codec
    whose encode emits a per-slab SIDECAR (int8's scale/zero point)
    cannot run on a multi-process mesh — each process encodes only its
    LOCAL shard, so the sidecars are per-process values, not the
    replicated globals a ``shard_map`` slab program's inputs must be
    (and gluing them in would re-introduce the cross-host bytes the
    codec exists to remove).  Sidecar-FREE codecs (``bf16``/``f16``/
    ``delta-f32``) stream on pods unchanged: every process encodes its
    own shard, so DCN/gloo ingest bytes shrink by the same wire ratio.
    Returns the message string, or ``None`` when the combination is
    fine."""
    if codec is None or not getattr(codec, "sidecar", False) \
            or mesh_process_count(mesh) <= 1:
        return None
    return ("codec %r carries a per-slab sidecar and cannot stream on "
            "a mesh spanning %d processes: per-process encodes produce "
            "per-process sidecars, which are not the replicated global "
            "inputs a shard_map slab program requires.  Use a "
            "sidecar-free codec ('bf16', 'f16', 'delta-f32') on pods, "
            "or stream this source uncompressed"
            % (codec.name, mesh_process_count(mesh)))
