"""Key-axis → mesh-axis assignment: the sharding spec IS the key/value split.

In the reference, key axes are the RDD record-key domain spread over Spark
partitions and value axes are the NumPy block each worker holds
(``bolt/spark/array.py :: BoltArraySpark`` state — symbol-level citation,
SURVEY.md §0).  Here the same split is expressed as a ``NamedSharding``: key
axes are mapped onto mesh axes (greedily, where sizes divide), value axes are
left unsharded/replicated.  Resharding between two such specs is what lowers
the reference's shuffle (``swap``/``chunk``) to XLA ``all_to_all`` collective
code over ICI.
"""

import itertools

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bolt_tpu.utils import prod

# exhaustive-assignment search bound: (split+1)**n_mesh_axes combinations
# (real meshes have <=4 axes, so the search is effectively always on)
_SEARCH_LIMIT = 4096


def spec_names(entry):
    """The mesh-axis names of one ``PartitionSpec`` entry as a tuple
    (entries are ``None``, one name, or a tuple of names)."""
    if entry is None:
        return ()
    return tuple(entry) if isinstance(entry, tuple) else (entry,)


def key_spec(mesh, shape, split, reserved=()):
    """A ``PartitionSpec`` sharding the leading ``split`` key axes over the
    mesh.  ``reserved`` mesh axes are never assigned (they belong to an
    explicit value-axis shard — see :func:`combined_spec`).

    Mesh axes are assigned to key axes greedily in order, and a key axis
    keeps absorbing further unused mesh axes while their combined size
    still divides it — so a single key axis on a multi-axis mesh shards
    over the WHOLE mesh (entry = a tuple of names) instead of leaving
    devices idle.  Unassigned axes (all value axes, and key axes nothing
    divides) are replicated — the exact analog of the reference's
    "records spread over partitions, block local to a worker".

    When the greedy order leaves devices idle that SOME assignment could
    use (e.g. keys ``(4, 2)`` on a mesh ``a=2, b=4``: greedy takes ``a``
    for the first key axis and strands ``b``), an exhaustive
    divisibility-matching search over all mesh-axis → key-axis
    assignments finds the utilization-optimal one.  The greedy result is
    kept whenever it is already optimal, so specs (and the sharding
    caches keyed on them) are stable for the common cases.
    """
    spec = [None] * len(shape)
    if mesh is not None:
        assigned = [[] for _ in range(split)]
        width = [1] * split
        used = set(reserved)
        # pass 1: one mesh axis per key axis, in order (every key axis
        # gets a chance before any axis takes a second)
        for i in range(split):
            for name in mesh.axis_names:
                if name in used or mesh.shape[name] <= 1:
                    continue
                if shape[i] % mesh.shape[name] == 0:
                    assigned[i].append(name)
                    width[i] = mesh.shape[name]
                    used.add(name)
                    break
        # pass 2: leftover mesh axes are absorbed where divisibility still
        # holds, so e.g. a lone key axis spreads over the WHOLE mesh
        for name in mesh.axis_names:
            if name in used or mesh.shape[name] <= 1:
                continue
            for i in range(split):
                if assigned[i] and shape[i] % (width[i] * mesh.shape[name]) == 0:
                    assigned[i].append(name)
                    width[i] *= mesh.shape[name]
                    used.add(name)
                    break
        candidates = [n for n in mesh.axis_names
                      if n not in reserved and mesh.shape[n] > 1]
        greedy_width = prod(width)
        full_width = prod([mesh.shape[n] for n in candidates])
        if greedy_width < full_width:
            best = _match_axes(mesh, shape, split, candidates, greedy_width)
            if best is not None:
                assigned = best
        for i in range(split):
            if len(assigned[i]) == 1:
                spec[i] = assigned[i][0]
            elif assigned[i]:
                spec[i] = tuple(assigned[i])
    return P(*spec)


def _match_axes(mesh, shape, split, candidates, floor):
    """Exhaustive mesh-axis → key-axis matching; returns per-key-axis name
    lists strictly beating ``floor`` devices utilized, else ``None``.

    Enumerates every assignment of each candidate mesh axis to one key
    axis (or none), keeps those where each key axis's combined width
    divides its size, and picks the one using the most devices.  Ties go
    to the first in enumeration order — mesh axes in name order preferring
    earlier key axes — so the result is deterministic."""
    if (split + 1) ** len(candidates) > _SEARCH_LIMIT:
        return None
    best, best_width = None, floor
    for choice in itertools.product(range(split + 1), repeat=len(candidates)):
        widths = [1] * split
        for name, ki in zip(candidates, choice):
            if ki < split:
                widths[ki] *= mesh.shape[name]
        if any(shape[i] % widths[i] != 0 for i in range(split)):
            continue
        total = prod(widths)
        if total > best_width:
            best_width = total
            best = [[n for n, ki in zip(candidates, choice) if ki == i]
                    for i in range(split)]
    return best


def combined_spec(mesh, shape, split, value_axes=None):
    """:func:`key_spec` plus explicit value-axis → mesh-axis assignments.

    ``value_axes`` maps a value-axis index (relative to the value group) to
    a mesh axis name — the sequence/context-parallel analog: the long
    contiguous dimension itself is split across devices (the reference
    scales such axes past one worker's memory with ``ChunkedArray`` blocks;
    SURVEY §2.4 maps that to value-axis sharding on the mesh)."""
    # reserve the explicitly requested mesh axes so key-axis absorption
    # cannot steal them
    reserved = tuple(value_axes.values()) if value_axes else ()
    spec = list(key_spec(mesh, shape, split, reserved=reserved))
    if value_axes:
        used = {n for s in spec for n in spec_names(s)}
        for va, name in value_axes.items():
            ax = split + va
            if ax < split or ax >= len(shape):
                raise ValueError("value axis %d out of range" % (va,))
            if name not in mesh.axis_names:
                raise ValueError("unknown mesh axis %r" % (name,))
            if name in used:
                raise ValueError("mesh axis %r already assigned" % (name,))
            if shape[ax] % mesh.shape[name] != 0:
                raise ValueError(
                    "value axis %d (size %d) is not divisible by mesh axis "
                    "%r (size %d)" % (va, shape[ax], name, mesh.shape[name]))
            spec[ax] = name
            used.add(name)
    return P(*spec)


def key_sharding(mesh, shape, split):
    """``NamedSharding`` for a bolt array of ``shape`` with ``split`` leading
    key axes (see :func:`key_spec`)."""
    return NamedSharding(mesh, key_spec(mesh, shape, split))


def device_placements(mesh, shape, split):
    """``(sharding, [(device, index)])``: the per-device sub-block layout
    of one host array of ``shape`` under the key sharding.

    ``index`` is the tuple of slices device ``d`` holds — ``block[index]``
    is exactly the sub-block to place on ``d``.  Replicated axes (key
    extents the mesh does not divide, and all value axes) repeat the full
    slice on every device.  The streaming executor's uploader pool uses
    this to ship one slab as independent per-device ``device_put`` calls
    (each worker uploads its slab's sub-blocks while other workers upload
    theirs) and then assembles the global array with
    :func:`assemble_from_parts` — no single-threaded whole-slab
    placement on the hot path."""
    sharding = key_sharding(mesh, shape, split)
    items = sharding.addressable_devices_indices_map(tuple(shape))
    return sharding, list(items.items())


def assemble_from_parts(shape, sharding, parts):
    """Glue per-device buffers (one per :func:`device_placements` entry,
    same order) into one global ``jax.Array`` — the zero-copy inverse of
    the placement map."""
    return jax.make_array_from_single_device_arrays(
        tuple(shape), sharding, parts)


def reshard(data, mesh, split):
    """Place ``data`` according to the key sharding for ``split``.

    Outside jit this is a ``device_put`` (XLA inserts the collective —
    all_to_all/all_gather — that the reference performs as a Spark shuffle;
    SURVEY.md §2.5 lowering contract), routed through the counted
    transfer layer (``bolt_tpu.stream.transfer``, lint rule BLT105) and
    recorded as a ``sharding.reshard`` span on the obs timeline (host
    uploads nest a ``stream.transfer`` child; device-side resharding is
    the ICI exchange the span's duration bounds)."""
    from bolt_tpu import stream
    from bolt_tpu.obs import trace as _obs
    with _obs.span("sharding.reshard", split=split,
                   bytes=int(getattr(data, "nbytes", 0))):
        return stream.transfer(data, key_sharding(mesh, data.shape, split))


def is_mesh(obj):
    """Dispatch predicate: is ``obj`` a device-mesh context?"""
    return isinstance(obj, Mesh)
