"""Key-axis → mesh-axis assignment: the sharding spec IS the key/value split.

In the reference, key axes are the RDD record-key domain spread over Spark
partitions and value axes are the NumPy block each worker holds
(``bolt/spark/array.py :: BoltArraySpark`` state — symbol-level citation,
SURVEY.md §0).  Here the same split is expressed as a ``NamedSharding``: key
axes are mapped onto mesh axes (greedily, where sizes divide), value axes are
left unsharded/replicated.  Resharding between two such specs is what lowers
the reference's shuffle (``swap``/``chunk``) to XLA ``all_to_all`` collective
code over ICI.
"""

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def spec_names(entry):
    """The mesh-axis names of one ``PartitionSpec`` entry as a tuple
    (entries are ``None``, one name, or a tuple of names)."""
    if entry is None:
        return ()
    return tuple(entry) if isinstance(entry, tuple) else (entry,)


def key_spec(mesh, shape, split, reserved=()):
    """A ``PartitionSpec`` sharding the leading ``split`` key axes over the
    mesh.  ``reserved`` mesh axes are never assigned (they belong to an
    explicit value-axis shard — see :func:`combined_spec`).

    Mesh axes are assigned to key axes greedily in order, and a key axis
    keeps absorbing further unused mesh axes while their combined size
    still divides it — so a single key axis on a multi-axis mesh shards
    over the WHOLE mesh (entry = a tuple of names) instead of leaving
    devices idle.  Unassigned axes (all value axes, and key axes nothing
    divides) are replicated — the exact analog of the reference's
    "records spread over partitions, block local to a worker".
    """
    spec = [None] * len(shape)
    if mesh is not None:
        assigned = [[] for _ in range(split)]
        width = [1] * split
        used = set(reserved)
        # pass 1: one mesh axis per key axis, in order (every key axis
        # gets a chance before any axis takes a second)
        for i in range(split):
            for name in mesh.axis_names:
                if name in used or mesh.shape[name] <= 1:
                    continue
                if shape[i] % mesh.shape[name] == 0:
                    assigned[i].append(name)
                    width[i] = mesh.shape[name]
                    used.add(name)
                    break
        # pass 2: leftover mesh axes are absorbed where divisibility still
        # holds, so e.g. a lone key axis spreads over the WHOLE mesh
        for name in mesh.axis_names:
            if name in used or mesh.shape[name] <= 1:
                continue
            for i in range(split):
                if assigned[i] and shape[i] % (width[i] * mesh.shape[name]) == 0:
                    assigned[i].append(name)
                    width[i] *= mesh.shape[name]
                    used.add(name)
                    break
        for i in range(split):
            if len(assigned[i]) == 1:
                spec[i] = assigned[i][0]
            elif assigned[i]:
                spec[i] = tuple(assigned[i])
    return P(*spec)


def combined_spec(mesh, shape, split, value_axes=None):
    """:func:`key_spec` plus explicit value-axis → mesh-axis assignments.

    ``value_axes`` maps a value-axis index (relative to the value group) to
    a mesh axis name — the sequence/context-parallel analog: the long
    contiguous dimension itself is split across devices (the reference
    scales such axes past one worker's memory with ``ChunkedArray`` blocks;
    SURVEY §2.4 maps that to value-axis sharding on the mesh)."""
    # reserve the explicitly requested mesh axes so key-axis absorption
    # cannot steal them
    reserved = tuple(value_axes.values()) if value_axes else ()
    spec = list(key_spec(mesh, shape, split, reserved=reserved))
    if value_axes:
        used = {n for s in spec for n in spec_names(s)}
        for va, name in value_axes.items():
            ax = split + va
            if ax < split or ax >= len(shape):
                raise ValueError("value axis %d out of range" % (va,))
            if name not in mesh.axis_names:
                raise ValueError("unknown mesh axis %r" % (name,))
            if name in used:
                raise ValueError("mesh axis %r already assigned" % (name,))
            if shape[ax] % mesh.shape[name] != 0:
                raise ValueError(
                    "value axis %d (size %d) is not divisible by mesh axis "
                    "%r (size %d)" % (va, shape[ax], name, mesh.shape[name]))
            spec[ax] = name
            used.add(name)
    return P(*spec)


def key_sharding(mesh, shape, split):
    """``NamedSharding`` for a bolt array of ``shape`` with ``split`` leading
    key axes (see :func:`key_spec`)."""
    return NamedSharding(mesh, key_spec(mesh, shape, split))


def reshard(data, mesh, split):
    """Place ``data`` according to the key sharding for ``split``.

    Outside jit this is ``jax.device_put`` (XLA inserts the collective —
    all_to_all/all_gather — that the reference performs as a Spark shuffle;
    SURVEY.md §2.5 lowering contract)."""
    return jax.device_put(data, key_sharding(mesh, data.shape, split))


def is_mesh(obj):
    """Dispatch predicate: is ``obj`` a device-mesh context?"""
    return isinstance(obj, Mesh)
