"""Back-compat alias for :mod:`bolt_tpu._precision`.

The implementation moved to ``bolt_tpu/_precision.py`` because the
package re-exports the :func:`~bolt_tpu._precision.precision` context
manager as ``bolt_tpu.precision`` — Python resolves
``import bolt_tpu.precision as p`` through the package ATTRIBUTE, so
that statement yields the context-manager function, not this module
(and always has).  Code that needs the module API must spell it

    from bolt_tpu._precision import resolve, MODES

``from bolt_tpu.precision import ...`` continues to work through this
alias for 0.4.x callers.
"""

import sys
import types

from bolt_tpu._precision import MODES, precision, resolve  # noqa: F401

__all__ = ["MODES", "precision", "resolve"]


class _CallableAlias(types.ModuleType):
    """Loading this alias module makes the import machinery setattr it
    onto the parent package AFTER this body runs — clobbering the
    re-exported context-manager function, so a later
    ``bolt_tpu.precision("default")`` would hit a module object.  Making
    the module itself callable (delegating to the context manager) keeps
    both spellings working in either order."""

    def __call__(self, mode):
        return precision(mode)


sys.modules[__name__].__class__ = _CallableAlias
