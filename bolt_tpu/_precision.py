"""Scoped matmul-precision policy for the MXU-bound op families.

TPU matmuls run bf16 passes on the MXU by default; this framework pins
its matmul-class ops (``@``/``dot``, ``np.einsum``/``tensordot``/
``inner``, the pca/cov/corrcoef Gram programs, the banded-matmul lane
filters behind ``smooth``/``gaussian``/``convolve``) at jax precision
``"highest"`` — f32 accumulation, ulp-level parity with the NumPy
oracle.  That parity costs a measured ~2x on the pca/halo perf
families (BASELINE round-4 MFU table: the bound is precision-caused,
not bandwidth-caused).  This module makes the documented trade
user-accessible without changing any default (VERDICT r4 weak-3/4):

    import bolt
    with bolt.precision("default"):       # bf16 MXU passes, ~2x faster
        scores, comps, sv = bolt.ops.pca(b, k=16)
        smoothed = bolt.ops.gaussian(b, sigma=4.0)

Modes map 1:1 onto ``jax.lax.Precision``:

- ``"default"``  — one bf16 pass per operand (fastest, ~1e-2 relative)
- ``"high"``     — three bf16 passes (f32-class accuracy, ~1.5x cost)
- ``"highest"``  — f32/f64 arithmetic (the pinned library default)

Resolution order: an explicit per-call ``precision=`` kwarg wins, then
the innermost active ``with bolt.precision(...)`` scope, then the op's
pinned default.  The scope is thread-local (safe under threaded
dispatch) and purely a TRACE-TIME choice: each compiled executable is
keyed on the resolved mode, so scoped and unscoped calls never share a
cache entry.

The local (NumPy oracle) backend computes in f64 regardless — the
policy is a device-side knob, which is exactly the parity story: under
``"highest"`` the suites hold their tight tolerances, under
``"default"`` the documented ~1e-2 relative envelope applies
(tests/test_precision.py pins both).
"""

import threading
from contextlib import contextmanager

MODES = ("default", "high", "highest")

_tls = threading.local()


def _check(mode):
    """Validate/coerce one precision spelling to a mode string.

    Accepts the three mode strings (any case) and ``jax.lax.Precision``
    enum members — the 0.4.0 ``dot(..., precision=...)`` contract took
    any jax precision spelling, so ``Precision.HIGHEST`` must keep
    working rather than ValueError-ing (ADVICE r5)."""
    try:
        from jax import lax
        if isinstance(mode, lax.Precision):
            return mode.name.lower()
    except ImportError:                       # pragma: no cover
        pass
    if isinstance(mode, str) and mode.lower() in MODES:
        return mode.lower()
    raise ValueError(
        "precision mode must be one of %r or a jax.lax.Precision "
        "member (got %r)" % (MODES, mode))


@contextmanager
def precision(mode):
    """Scoped precision policy: every matmul-class op traced inside the
    ``with`` block uses ``mode`` unless the call passes its own
    ``precision=``.  Nests (innermost wins); defaults are unchanged
    outside any scope."""
    mode = _check(mode)
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    st.append(mode)
    try:
        yield
    finally:
        st.pop()


def resolve(explicit=None, pinned="highest"):
    """The effective jax precision for one call: ``explicit`` per-call
    kwarg > innermost active scope > the op's ``pinned`` default."""
    if explicit is not None:
        return _check(explicit)
    st = getattr(_tls, "stack", None)
    if st:
        return st[-1]
    return pinned


# ---------------------------------------------------------------------
# reduced-precision ACCUMULATION for the fused multi-stat reductions
# (bolt.compute / stats(...) — bolt_tpu/tpu/multistat.py).  A separate
# axis from the matmul precision above: matmul precision picks the MXU
# pass count, accumulation mode picks the value/accumulator dtypes of
# the additive reduction terminals (sum/prod/mean/var/std).
#
# - None (default): bit-identical to the standalone terminals — the
#   fused program traces exactly the standalone expressions.
# - "f32": values cast to float32 before reducing (results float32).
#   For float32 pipelines this is EXACTLY the default arithmetic
#   (parity-locked bit-identical in tests); for float64 pipelines it is
#   the documented downcast (~1e-7 relative).
# - "bf16": values cast to bfloat16, accumulated in float32 (the
#   accumulate-in-f32 contract; results float32).  Halves the read
#   bytes of a bf16-resident pipeline and keeps the documented ~1e-2
#   relative accuracy envelope (parity-locked at that tolerance in
#   tests/test_multistat.py).
#
# - "int8": the INTEGER twin of the bf16 path — values of an
#   integer-dtype pipeline cast to int8, accumulated in int32 (the
#   accumulate-in-i32 contract; results int32).  Applies to the integer
#   additive terminals (sum/prod) only: mean/var/std are float-valued
#   and ignore it, as do float pipelines.  The documented envelope is
#   EXACT integer arithmetic for values in int8 range ([-128, 127]) —
#   out-of-range values wrap (two's complement), which is the caller's
#   contract to uphold (parity-locked in tests/test_multistat.py
#   alongside the bf16 suite).
#
# min/max/any/all (and the min/max pair behind ptp) are exact order
# statistics and ignore the mode.  Scoped like bolt.precision
# (thread-local, innermost wins); the per-call door is
# ``bolt.compute(..., accumulate=...)``.
# ---------------------------------------------------------------------

ACCUMULATE_MODES = ("bf16", "f32", "int8")

_acc_tls = threading.local()


def _check_accumulate(mode):
    if mode is None:
        return None
    if isinstance(mode, str) and mode.lower() in ACCUMULATE_MODES:
        return mode.lower()
    raise ValueError(
        "accumulate mode must be one of %r or None (got %r)"
        % (ACCUMULATE_MODES, mode))


@contextmanager
def accumulate(mode):
    """Scoped reduced-precision accumulation for fused multi-stat
    reductions::

        with bolt_tpu._precision.accumulate("bf16"):
            s, v = bolt.compute(b.sum(), b.var())

    ``accumulate(None)`` restores the exact default inside the scope.
    Nests (innermost wins); defaults are unchanged outside any scope."""
    mode = _check_accumulate(mode)
    st = getattr(_acc_tls, "stack", None)
    if st is None:
        st = _acc_tls.stack = []
    st.append(mode)
    try:
        yield
    finally:
        st.pop()


def resolve_accumulate(explicit=None):
    """The effective accumulation mode for one fused dispatch:
    ``explicit`` (``bolt.compute(..., accumulate=...)``) > innermost
    active :func:`accumulate` scope > ``None`` (exact, the default)."""
    if explicit is not None:
        return _check_accumulate(explicit)
    st = getattr(_acc_tls, "stack", None)
    if st:
        return st[-1]
    return None


# ---------------------------------------------------------------------
# codec-encoded ingest accuracy contract (bolt_tpu/tpu/codec.py,
# ISSUE 14) — the third precision axis, same template as accumulate():
# the default (no codec) is bit-exact; lossy codecs are an explicit
# per-source/per-scope opt-in with the parity envelopes below, which
# tests/test_codec.py locks streamed results against.  Order statistics
# and integer pipelines refuse lossy codecs at the executor (quantised
# min/max is never what the caller meant); the lossless "delta-f32"
# codec is bit-identical by construction and accepted everywhere.
# ---------------------------------------------------------------------

# codec name -> (lossless, documented relative-error envelope vs the
# uncompressed streamed result; None = bit-identical).  int8's envelope
# is ABSOLUTE per element (~half the per-slab quantisation step,
# value-range dependent) — tests derive the concrete bound from each
# slab's range, like the int8-accumulate wraparound contract.
CODEC_BOUNDS = {
    "bf16": (False, 1e-2),
    "f16": (False, 1e-3),
    "int8": (False, "~scale/2 absolute (scale = slab range / 255)"),
    "delta-f32": (True, None),
}


def codec_bound(name):
    """``(lossless, envelope)`` for a registered codec name — the
    documented parity contract the codec suite asserts.  Unknown names
    return ``(False, None)`` (a custom registered codec documents its
    own bound)."""
    return CODEC_BOUNDS.get(name, (False, None))
