"""Streaming out-of-core executor: a parallel-ingest, async-dispatch
host↔device pipeline.

Every other execution path in this backend materialises its operand fully
in device memory before a terminal runs, which caps the workload class at
HBM.  This module opens datasets LARGER than device memory: a lazy
:class:`StreamSource` describes host-resident data as a sequence of
record *slabs* (consecutive blocks along the first key axis) plus a chain
of device-side stages (per-record maps, chunked maps, stacked maps, a
trailing filter predicate), and :func:`execute` runs a reduction terminal
over it as a pipelined fan-in:

* an **N-way uploader pool** (default ``min(mesh devices, 4)``;
  ``BOLT_STREAM_UPLOAD_THREADS`` / the :func:`uploaders` scope) ingests
  slabs concurrently — for random-access ``fromcallback`` sources each
  worker produces AND uploads its own slab (per-device sub-blocks via
  ``parallel.sharding.device_placements``), so one CPU thread is never
  the bottleneck feeding many chips; sequential ``fromiter`` sources
  keep one produce+upload prefetch thread.  A **re-sequencer** hands
  completed slabs to the consumer strictly in slab order, so the fold
  is deterministic and bit-exact regardless of upload completion order;
* slab buffers form a **ring** bounded by ``prefetch depth + pool
  size``, and each is **donated** into its per-slab program
  (``donate_argnums``), so XLA recycles the ring's device memory
  instead of allocating per slab;
* slab programs **dispatch asynchronously** into a bounded in-flight
  window — no per-slab ``block_until_ready``; the consumer syncs only
  on window overflow (an already-retired old partial, ~free) and on the
  final result, so device compute and host ingest overlap fully;
* reduction terminals fold per-slab partials ON DEVICE — the **level-0
  fold is fused into the slab program** (odd slabs run ``prog(buf,
  acc)``, merging with the preceding slab's partial in the same
  dispatch — half the fold dispatches), and a pairwise tree of
  ``add``/``func`` merges for ``sum``/``reduce``, a Welford/Chan
  statcounter-moment merge (``n, μ, M2``) for ``mean``/``var``/``std``,
  combines pair-partials above level 0 — so host traffic is one slab
  in, one value-block out, and power-of-two slab counts keep the Chan
  denominators exact.

The per-slab program applies the SAME traced bodies the materialised
paths compile (``tpu/chunk.py :: _uniform_map_body`` /
``_general_map_body``, ``tpu/stack.py :: _stack_map_body``,
``tpu/array.py :: _chain_apply`` / ``_pred_mask``), so streamed and
materialised results cannot drift semantically — the out-of-core parity
suite (``tests/test_stream.py``) bit-compares them.

Accounting lands in the engine counters (``transfer_bytes`` /
``transfer_seconds`` for every counted upload, the ``stream_*`` family
for the executor — including ``stream_upload_threads``, the observed
concurrent-uploader high-water, and ``stream_inflight_high_water``, the
async dispatch window's peak).  Ingest/compute seconds are attributed
from the same instrumented regions the obs spans cover (worker
``stream.ingest`` spans, consumer ``stream.compute`` dispatch +
``stream.sync`` windows), NOT from wall-clock around a per-slab sync;
:func:`bolt_tpu.profile.overlap_efficiency` reports the fraction of
ingest time hidden behind device compute — ``max(0, ingest + compute -
wall) / ingest`` per run.

Fault model (ISSUE 9 made it three-tiered):

* **fail-fast** (the default): a source callback or uploader worker that
  raises mid-stream aborts cleanly — the whole pool is joined, queued
  ring buffers are released, the partial reduction state is discarded,
  and the ORIGINAL exception is re-raised to the caller.  A pool thread
  that dies WITHOUT delivering (interpreter teardown, a killed thread)
  is detected by the consumer's liveness poll, which raises a pointed
  ``RuntimeError`` naming the dead thread instead of blocking forever;
* **in-run retry** (``stream.retries(n)`` / ``BOLT_STREAM_RETRIES``): a
  failed slab ingest is re-attempted up to *n* times before poisoning
  the run — the slab re-runs in place on its worker, fenced through the
  re-sequencer so a late duplicate of an earlier attempt can never
  double-fold, and when the budget exhausts the final error chains every
  attempt's exception back to the original failure;
* **resume** (``stream.resumable(dir)`` / ``fromcallback``/``fromiter``
  ``checkpoint=dir``): every ``BOLT_CHECKPOINT_EVERY`` retired slabs the
  executor drains its async window and persists the retired-slab
  watermark plus the folded partial accumulator (pairwise-tree levels +
  the unpaired pair partial — moment triples and fused multi-stat
  tuples included) via ``bolt_tpu.checkpoint.stream_save``.  A killed
  run (preemption, ``kill -9``) restarted over the same source skips the
  already-retired slabs, reloads the exact fold state, and produces a
  result BIT-IDENTICAL to the uninterrupted run — the fold is a
  deterministic function of (slab order, accumulator state), both of
  which the checkpoint captures exactly.  A finished run clears its
  checkpoint (no stale files).  Deterministic fault points for all of
  this live in ``bolt_tpu._chaos`` (seams: ``stream.upload``,
  ``stream.dispatch``, ``stream.fold``, ``stream.checkpoint``).

POD SCALE (``bolt_tpu.parallel.multihost``): on a mesh spanning
PROCESSES this same executor runs as N peers over one deterministic
slab schedule.  Each process produces and uploads ONLY its own
contiguous shard of every slab (``multihost.local_slab_spec`` — the
``fromcallback(..., per_process=True)`` contract; ``fromiter``
re-iterable sources slice their shard out of each global block), the
global slab array is glued from local parts with zero cross-host
motion, and the slab program runs under ``shard_map`` with the
cross-host fold as mesh-axis collectives (``psum`` for sum and the
moment components, ``pmin``/``pmax`` for order statistics) — so one
streamed slab costs one collective (two for moments) and every fold
partial comes back replicated.  Slabs dispatch in slab order on every
process, so the collective rendezvous can never cross; uneven slabs
refuse with the pointed BLT012 error before any thread starts; and
checkpoints become per-process shard files with a
rendezvous-consistent watermark (``checkpoint.stream_save``).  On even
splits the hierarchical sums equal the flat sums whenever the data
keeps the reduction exact, so results stay bit-identical to the
single-process run (tests/test_multihost.py proves it on a REAL
2-process ``jax.distributed`` localhost cluster).
"""

import contextlib
import os
import queue
import sys
import threading
import warnings
from collections import deque

import numpy as np

import jax
import jax.numpy as jnp

from bolt_tpu import _chaos
from bolt_tpu import _lockdep
from bolt_tpu import engine as _engine
from bolt_tpu.obs import trace as _obs
from bolt_tpu.obs.trace import clock as _clock
from bolt_tpu.parallel import multihost as _multihost
from bolt_tpu.parallel import podwatch as _podwatch
from bolt_tpu.utils import iter_record_blocks, prod

# ---------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------

# prefetch depth k: how many uploaded slabs may wait ahead of the
# consumer beyond the uploader pool's own hands-on slabs (the ring is
# bounded at depth + pool size).  2 = classic double buffering: one slab
# in compute, one in flight.  Deeper rings only help when per-slab
# ingest time is noisy; they cost one slab of HBM each.
_DEPTH = max(1, int(os.environ.get("BOLT_STREAM_DEPTH", "2")))

# uploader pool size: concurrent ingest workers.  0 = auto, resolved per
# run as min(mesh device count, 4) — one host thread cannot saturate the
# link feeding many chips, but past ~4 workers the host memory bus is
# the limit, not thread count.  Sequential (fromiter) sources always
# stream through ONE produce+upload prefetch thread regardless.
_UPLOADERS = max(0, int(os.environ.get("BOLT_STREAM_UPLOAD_THREADS",
                                       "0")))

# the prefetch()/uploaders() SCOPES are thread-local (like
# engine.donation and bolt.precision): under the multi-tenant serving
# layer (bolt_tpu.serve) concurrent streams run on different threads,
# and one tenant's `with uploaders(8)` must not inflate a neighbour's
# pool mid-run.  set_prefetch_depth/set_upload_threads change the
# PROCESS-WIDE default the scopes override.
_SCOPE_TLS = threading.local()


def _scope_stack(name):
    st = getattr(_SCOPE_TLS, name, None)
    if st is None:
        st = []
        setattr(_SCOPE_TLS, name, st)
    return st

# default slab budget when the caller gives no explicit record count:
# big enough to amortise per-dispatch overhead, small enough that
# depth+1 slabs stay far below any device's HBM
_SLAB_BYTES = int(os.environ.get("BOLT_STREAM_SLAB_BYTES", str(64 << 20)))


def prefetch_depth():
    """The active prefetch (ring) depth for the CALLING THREAD: the
    innermost :func:`prefetch` scope on this thread, else the
    process-wide default."""
    st = _scope_stack("depth")
    if st:
        return st[-1]
    return _DEPTH


def set_prefetch_depth(k):
    """Set the process-wide DEFAULT prefetch depth (ring size), >= 1;
    per-thread :func:`prefetch` scopes override it."""
    global _DEPTH
    _DEPTH = max(1, int(k))


@contextlib.contextmanager
def prefetch(depth):
    """Scope the prefetch depth::

        with bolt_tpu.stream.prefetch(4):
            big.chunk().map(f).mean()

    The scope is THREAD-LOCAL: a concurrent stream on another thread
    (another serve tenant) keeps its own value — one tenant's deep ring
    must not silently multiply a neighbour's device-memory footprint."""
    st = _scope_stack("depth")
    st.append(max(1, int(depth)))
    try:
        yield
    finally:
        st.pop()


def upload_threads():
    """The configured uploader-pool size for the calling thread
    (innermost :func:`uploaders` scope, else the process default;
    0 = auto: resolved per run as ``min(mesh devices, 4)``)."""
    st = _scope_stack("uploaders")
    if st:
        return st[-1]
    return _UPLOADERS


def set_upload_threads(n):
    """Set the process-wide DEFAULT uploader-pool size (0 restores
    auto); per-thread :func:`uploaders` scopes override it."""
    global _UPLOADERS
    _UPLOADERS = max(0, int(n))


@contextlib.contextmanager
def uploaders(n):
    """Scope the uploader-pool size (``0`` = auto, like
    :func:`set_upload_threads`)::

        with bolt_tpu.stream.uploaders(8):
            src.map(f).sum()

    THREAD-LOCAL, like :func:`prefetch` — concurrent streams on other
    threads resolve their own scopes (regression-locked in
    tests/test_stream.py)."""
    st = _scope_stack("uploaders")
    st.append(max(0, int(n)))
    try:
        yield
    finally:
        st.pop()


# in-run retry budget per slab: 0 = fail-fast (today's behavior), n = a
# failed slab ingest re-attempts up to n times before poisoning the run
_RETRIES = max(0, int(os.environ.get("BOLT_STREAM_RETRIES", "0")))

# checkpoint cadence under resumable(): persist the fold state every k
# retired slabs.  Each write drains the async window and pulls the
# (value-shaped, small) partials to host — frequent checkpoints buy a
# tighter resume point at a per-write pipeline stall.
_CKPT_EVERY = max(1, int(os.environ.get("BOLT_CHECKPOINT_EVERY", "2")))


def retry_limit():
    """The active per-slab retry budget for the calling thread
    (innermost :func:`retries` scope, else the process default;
    0 = fail-fast)."""
    st = _scope_stack("retries")
    if st:
        return st[-1]
    return _RETRIES


def set_retries(n):
    """Set the process-wide DEFAULT per-slab retry budget; per-thread
    :func:`retries` scopes override it."""
    global _RETRIES
    _RETRIES = max(0, int(n))


@contextlib.contextmanager
def retries(n):
    """Scope the per-slab ingest retry budget::

        with bolt_tpu.stream.retries(2):
            flaky_src.map(f).sum()       # each slab survives 2 failures

    THREAD-LOCAL like :func:`prefetch`/:func:`uploaders`: a serve
    tenant's retry policy must not leak into a neighbour's run."""
    st = _scope_stack("retries")
    st.append(max(0, int(n)))
    try:
        yield
    finally:
        st.pop()


# codec-encoded ingest (ISSUE 14, bolt_tpu/tpu/codec.py): the process
# default codec NAME the thread-local codec() scopes override; None =
# uncompressed.  Lazily validated against the registry so merely
# importing stream never touches the codec module.
_CODEC = os.environ.get("BOLT_STREAM_CODEC") or None


def _codec_registry():
    from bolt_tpu.tpu import codec as m
    return m


def current_codec():
    """The calling thread's effective codec NAME (innermost
    :func:`codec` scope, else the process default; ``None`` =
    uncompressed).  A source's own ``codec=`` always wins over this —
    see :func:`resolve_codec`."""
    st = _scope_stack("codec")
    if st:
        return st[-1]
    return _CODEC


def set_codec(name):
    """Set the process-wide DEFAULT ingest codec (``None`` restores
    uncompressed; ``BOLT_STREAM_CODEC`` seeds it); per-thread
    :func:`codec` scopes override it."""
    global _CODEC
    if name is not None:
        _codec_registry().get(name)     # pointed unknown-codec error NOW
    _CODEC = name


@contextlib.contextmanager
def codec(name):
    """Scope codec-encoded ingest for streamed runs::

        with bolt_tpu.stream.codec("bf16"):
            src.map(f).sum()     # slabs ship at half the bytes; the
                                 # slab program decodes on device

    ``codec(None)`` restores uncompressed ingest inside the scope.
    THREAD-LOCAL with the same stack discipline as :func:`uploaders` /
    :func:`prefetch`: one serve tenant's lossy opt-in must never
    silently quantise a neighbour's stream — and ``serve.submit``
    captures the SUBMITTER's effective codec and re-enters it on the
    worker thread, so a scope wrapped around a submit is honoured by
    the job (and priced by admission) rather than dropped at the
    thread boundary.  A per-source
    ``fromcallback(..., codec=)`` / ``fromiter(..., codec=)`` takes
    precedence over the scope (mirroring ``checkpoint=``).  The
    accuracy contract lives with the registry
    (:mod:`bolt_tpu.tpu.codec`): lossless ``"delta-f32"`` is
    bit-identical to uncompressed streaming; lossy codecs are refused
    for order-statistic terminals and non-float pipelines."""
    if name is not None:
        _codec_registry().get(name)     # validate at scope entry
    st = _scope_stack("codec")
    st.append(name)
    try:
        yield
    finally:
        st.pop()


def resolve_codec(source):
    """The effective :class:`~bolt_tpu.tpu.codec.Codec` for a run over
    ``source`` — the source's own ``codec=`` wins over the calling
    thread's scope/default; ``None`` = uncompressed.  Validates the
    codec against the source dtype (the pointed integer/bool-pipeline
    refusal lives in ``Codec.wire_dtype``)."""
    name = source.codec if source.codec is not None else current_codec()
    if name is None:
        return None
    c = _codec_registry().get(name)
    c.wire_dtype(source.dtype)
    return c


def checkpoint_scope():
    """The calling thread's innermost :func:`resumable` scope as
    ``(dir, every)``, or ``None`` when streaming is not resumable."""
    st = _scope_stack("ckpt")
    return st[-1] if st else None


@contextlib.contextmanager
def resumable(dir, every=None):
    """Scope slab-level checkpointing for streamed runs::

        with bolt_tpu.stream.resumable("/ckpt/run17"):
            src.map(f).sum()     # killed?  re-run resumes from the last
                                 # retired slab, bit-identically

    ``every`` is the checkpoint cadence in retired slabs (default
    ``BOLT_CHECKPOINT_EVERY``, 2).  THREAD-LOCAL; a per-source
    ``checkpoint=dir`` (``fromcallback``/``fromiter``) takes precedence
    over the scope.  One-shot iterator sources cannot be resumed (the
    iterator dies with the process) — ``analysis.check`` flags that
    shape as BLT011."""
    st = _scope_stack("ckpt")
    st.append((os.fspath(dir),
               max(1, int(every)) if every is not None else _CKPT_EVERY))
    try:
        yield
    finally:
        st.pop()


# out-of-core shuffle spill (ISSUE 18): the process default spill
# directory for streamed-swap resolutions whose output exceeds the
# resident budget; None = no spill dir (a spill-forecast resolution
# then refuses pointedly — BLT017 warns ahead of time).
_SPILL_DIR = os.environ.get("BOLT_STREAM_SPILL_DIR") or None


@contextlib.contextmanager
def spill(dir=None, budget=None):
    """Scope the out-of-core shuffle's spill policy::

        with bolt_tpu.stream.spill("/scratch/shuffle", budget=1 << 30):
            big.swap([1], [0]).sum()   # re-keyed buckets larger than
                                       # 1 GiB spill to encoded files

    ``dir`` is where spilled bucket files land (``None`` keeps the
    ``BOLT_STREAM_SPILL_DIR`` default); ``budget`` caps the RESIDENT
    working set in bytes (``None`` defers to the serving arbiter's
    budget, else unbounded).  THREAD-LOCAL with the same stack
    discipline as :func:`codec`/:func:`resumable`: one serve tenant's
    spill policy must not redirect a neighbour's bucket files."""
    st = _scope_stack("spill")
    st.append((os.fspath(dir) if dir is not None else None,
               int(budget) if budget is not None else None))
    try:
        yield
    finally:
        st.pop()


def spill_scope():
    """The calling thread's innermost :func:`spill` scope as
    ``(dir, budget)`` — dir falling back to ``BOLT_STREAM_SPILL_DIR``,
    budget ``None`` when unset."""
    st = _scope_stack("spill")
    if st:
        d, b = st[-1]
        return (d if d is not None else _SPILL_DIR), b
    return _SPILL_DIR, None


def swap_budget():
    """The resident-working-set ceiling a streamed-swap resolution
    plans against: the innermost :func:`spill` scope's explicit
    ``budget``, else the ACTIVE serving arbiter's device budget, else
    ``None`` (unbounded — always resident).  The checker's BLT017
    forecast calls this same function, so the forecast and the
    measured resident/spill decision cannot drift."""
    _, b = spill_scope()
    if b is not None:
        return b
    sv = sys.modules.get("bolt_tpu.serve")
    if sv is None:
        return None
    arb = sv.device_arbiter()
    if arb is None:
        return None
    return int(arb.budget)


def pool_size(source):
    """The uploader-pool size a run over ``source`` will use: the
    calling thread's configured count (scope/env), else ``min(mesh
    devices, 4)``; sequential ``fromiter`` sources always use ONE
    prefetch thread (their iterator cannot be consumed concurrently)."""
    if source.kind != "callback":
        return 1
    n = upload_threads()
    if n >= 1:
        return n
    ndev = int(source.mesh.devices.size) if source.mesh is not None else 1
    return min(max(ndev, 1), 4)


def _cached_jit(key, builder):
    """Engine-routed executable dispatch (same contract as the op
    modules'; ``bolt_tpu.profile.instrument`` patches this name)."""
    return _engine.get(key, builder)


def _tenant_lease():
    """A device-memory lease from the ACTIVE serving arbiter
    (``bolt_tpu.serve``), attributed to the calling thread's tenant —
    or ``None`` when no serving layer is running.  Consulted through
    ``sys.modules`` so merely streaming never imports (or starts) the
    serving layer; with a lease in hand the executor's slab uploads
    charge the process-wide bytes budget instead of assuming sole
    ownership of device memory."""
    sv = sys.modules.get("bolt_tpu.serve")
    if sv is None:
        return None
    arb = sv.device_arbiter()
    if arb is None:
        return None
    return arb.lease(_engine.current_tenant() or "default")


# ---------------------------------------------------------------------
# the counted transfer layer (lint rule BLT105: the only raw
# jax.device_put in the package lives here)
# ---------------------------------------------------------------------

def transfer(x, sharding=None, wait=False):
    """Counted data placement: ``jax.device_put`` with engine accounting.

    Host sources (anything that is not already a ``jax.Array``) tally
    their bytes into the engine's ``transfer_bytes``/``transfer_seconds``
    counters; device-resident inputs (resharding — an ICI exchange, not
    host traffic) pass through uncounted.  EVERY counted upload blocks
    until the buffer lands before its seconds are recorded — otherwise
    ``transfer_seconds`` would tally async-dispatch time against the
    full payload's bytes and report impossible GB/s (``wait`` is kept
    for call-site documentation; the prefetch thread's blocking is the
    point there — it is off the critical path, and host ``device_put``
    is a synchronous copy in practice everywhere else)."""
    host = not isinstance(x, jax.Array)
    sp = _obs.begin("stream.transfer") if host else None
    t0 = _clock()
    try:
        out = jax.device_put(x, sharding) if sharding is not None \
            else jax.device_put(x)
        if host:
            out.block_until_ready()
            nbytes = getattr(x, "nbytes", None)
            if nbytes is None:
                nbytes = np.asarray(x).nbytes
            _engine.record_transfer(int(nbytes), _clock() - t0)
            if sp is not None:
                sp.set(bytes=int(nbytes), wait=wait)
    finally:
        _obs.end(sp)
    return out


def _upload_slab(block, mesh, split):
    """Upload ONE host slab as its per-device sub-blocks and assemble
    the global sharded array — the uploader-pool hot path.

    Per-device placement (``parallel.sharding.device_placements``) keeps
    each worker's uploads independent: N workers each ``device_put``
    their own slab's sub-blocks concurrently, with no shared whole-slab
    placement call serialising them.  Counted ONCE per slab (logical
    host bytes, like :func:`transfer` — replication is a placement
    detail, not payload), and every sub-block is blocked on before the
    seconds are recorded, so ``transfer_seconds`` stays honest.  The
    degenerate case of :func:`_upload_slab_mh` — the local range is the
    whole slab."""
    return _upload_slab_mh(block, mesh, split, block.shape, 0)


def _upload_slab_mh(block, mesh, split, slab_shape, axis0_off):
    """Upload THIS PROCESS's sub-block of one slab and assemble the
    global sharded array — the ONE uploader hot path (single-process
    through :func:`_upload_slab`, pod-scale directly under the
    ``bolt_tpu.parallel.multihost`` per-process contract).

    ``block`` holds this process's contiguous record range of a slab of
    ``slab_shape`` (the whole slab single-process); ``axis0_off`` is
    that range's offset within the slab.  Parts are placed on the
    process's ADDRESSABLE devices only (the index map never names
    remote devices), and the global array is glued with
    ``make_array_from_single_device_arrays`` — no cross-host data
    motion happens at ingest; the cross-host combine is the slab
    program's mesh collective.  Counted at the LOCAL bytes, so
    ``transfer_bytes``/GB-per-second report each process's own link."""
    from bolt_tpu.parallel import sharding as _sh
    _chaos.hit("stream.upload")
    sp = _obs.begin("stream.transfer")
    t0 = _clock()
    try:
        sharding, placements = _sh.device_placements(mesh, slab_shape,
                                                     split)
        parts = []
        for dev, index in placements:
            lo0, hi0, _ = index[0].indices(slab_shape[0])
            local = (slice(lo0 - axis0_off, hi0 - axis0_off),) \
                + tuple(index[1:])
            parts.append(jax.device_put(block[local], dev))
        for p in parts:
            p.block_until_ready()
        out = _sh.assemble_from_parts(slab_shape, sharding, parts)
        nbytes = int(block.nbytes)
        _engine.record_transfer(nbytes, _clock() - t0)
        if sp is not None:
            sp.set(bytes=nbytes, parts=len(parts))
    finally:
        _obs.end(sp)
    return out


def _encode_slab(codec_obj, block, delta_ok):
    """Host-side slab ENCODE on an uploader worker (ISSUE 14): the
    ``stream.encode`` chaos seam and obs span (``bytes_raw`` /
    ``bytes_wire`` attrs, nesting under the worker's ``stream.ingest``
    span) plus the ``codec_*`` engine counters all live here.  Encode
    runs per worker, so N workers encode N slabs concurrently — the
    encode cost rides inside the already-overlapped ingest phase."""
    _chaos.hit("stream.encode")
    sp = _obs.begin("stream.encode", codec=codec_obj.name)
    t0 = _clock()
    try:
        wire, side = codec_obj.encode(block, delta_ok)
        _engine.record_codec(int(block.nbytes), int(wire.nbytes),
                            _clock() - t0)
        if sp is not None:
            sp.set(bytes_raw=int(block.nbytes),
                   bytes_wire=int(wire.nbytes))
    finally:
        _obs.end(sp)
    return wire, side


# ---------------------------------------------------------------------
# the lazy source
# ---------------------------------------------------------------------

class StreamSource:
    """A lazy out-of-core operand: host slabs + device-side stages.

    ``kind='callback'`` sources produce any record range on demand
    (``fn(index_slices) -> block``, the ``fromcallback`` contract) and
    can be streamed repeatedly; ``kind='iter'`` sources
    (``fromiter``) yield consecutive blocks and stream in order, once
    per ``iter()`` of the underlying iterable.

    ``stages`` is the device-side chain, applied per slab inside ONE
    compiled program: ``("map", func)`` per-record, ``("chunk", func,
    plan, pad, canon)``, ``("stack", func, size, canon)``, and a
    trailing ``("filter", pred)`` whose mask the reduction terminals
    fold without ever materialising a compaction buffer."""

    __slots__ = ("kind", "produce", "blocks", "shape", "split", "dtype",
                 "mesh", "slab", "stages", "ckpt", "codec", "_state",
                 "_consumed")

    def __init__(self, kind, produce, blocks, shape, split, dtype, mesh,
                 slab, stages=(), ckpt=None, codec=None):
        self.kind = kind
        self.produce = produce          # callback: fn(index_slices)
        self.blocks = blocks            # iter: the iterable of blocks
        self.shape = tuple(int(s) for s in shape)
        self.split = int(split)
        self.dtype = np.dtype(dtype)
        self.mesh = mesh
        self.slab = int(slab)
        self.stages = tuple(stages)
        self.ckpt = ckpt                # resumable checkpoint dir (or None)
        self.codec = codec              # ingest codec NAME (or None);
        #                                 wins over the codec() scope
        self._state = None
        # iter sources stream ONCE per iter() of a one-shot iterable (a
        # generator cannot rewind); the cell is SHARED across derived
        # sources (with_stage) because they share the iterator itself
        self._consumed = [False]

    # -- construction --------------------------------------------------

    @classmethod
    def from_callback(cls, fn, shape, split, dtype, mesh, chunks=None,
                      checkpoint=None, codec=None):
        if codec is not None:
            # a typo'd codec name must be a pointed error HERE, at the
            # construction boundary — not a crash inside the checker or
            # a first-terminal surprise (dtype fit still resolves per
            # run: the scope form can override a None source codec)
            _codec_registry().get(codec)
        slab = _slab_records(shape, dtype, chunks)
        return cls("callback", fn, None, shape, split, dtype, mesh, slab,
                   ckpt=checkpoint, codec=codec)

    @classmethod
    def from_iter(cls, blocks, shape, split, dtype, mesh,
                  checkpoint=None, codec=None):
        if codec is not None:
            _codec_registry().get(codec)    # pointed at construction
        # slab sizes are whatever the iterator yields; the recorded slab
        # is only the default the shape/dtype imply (for repr/reports)
        slab = _slab_records(shape, dtype, None)
        return cls("iter", None, blocks, shape, split, dtype, mesh, slab,
                   ckpt=checkpoint, codec=codec)

    def with_stage(self, stage):
        """A new source sharing the host side, one device stage longer."""
        out = StreamSource(self.kind, self.produce, self.blocks,
                           self.shape, self.split, self.dtype, self.mesh,
                           self.slab, self.stages + (stage,),
                           ckpt=self.ckpt, codec=self.codec)
        out._consumed = self._consumed      # same iterator, same budget
        return out

    # -- the host slab iterator ---------------------------------------

    def produce_slab(self, lo, hi):
        """Produce ONE validated host block for records ``[lo, hi)`` —
        the random-access path the uploader-pool workers call
        CONCURRENTLY (callback sources only; the callback must therefore
        be thread-safe, which slicing a memmap/HDF5-style store is)."""
        rest = self.shape[1:]
        index = (slice(lo, hi),) + tuple(slice(0, s) for s in rest)
        block = np.asarray(self.produce(index), dtype=self.dtype)
        if block.shape != (hi - lo,) + rest:
            raise ValueError(
                "fromcallback callback returned shape %s for index "
                "%s (expected %s)"
                % (block.shape, index, (hi - lo,) + rest))
        return block

    def slab_ranges(self):
        """``(lo, hi)`` record ranges of every slab, in key order."""
        n, slab = self.shape[0], self.slab
        return [(lo, min(lo + slab, n)) for lo in range(0, n, slab)]

    def slabs(self):
        """Yield ``(lo, hi, block)`` record slabs in key order; blocks
        are validated and cast to the source dtype.  Callback sources
        slice on demand; iterator sources stream whatever block sizes
        the iterable yields and must cover the shape exactly."""
        if self.kind == "callback":
            for lo, hi in self.slab_ranges():
                yield lo, hi, self.produce_slab(lo, hi)
            return
        # one-shot iterables (iter(x) is x: generators, file readers)
        # cannot stream twice — raise a POINTED error instead of the
        # misleading "blocks cover only 0 of N records" the exhausted
        # iterator would otherwise produce downstream
        if iter(self.blocks) is self.blocks:
            if self._consumed[0]:
                raise RuntimeError(
                    "this fromiter source was already streamed and its "
                    "iterator is exhausted (generators are one-shot); "
                    "materialise once and reuse the result, pass a "
                    "re-iterable (e.g. a list of blocks), or use "
                    "fromcallback for random-access sources")
            self._consumed[0] = True
        yield from iter_record_blocks(self.blocks, self.shape, self.dtype)

    def __repr__(self):
        return ("StreamSource(%s, shape=%s, split=%d, dtype=%s, slab=%d, "
                "stages=%d)" % (self.kind, self.shape, self.split,
                                self.dtype, self.slab, len(self.stages)))


def _slab_records(shape, dtype, chunks):
    n = int(shape[0])
    if chunks is not None:
        slab = int(chunks)
        if slab < 1:
            raise ValueError("chunks (records per slab) must be >= 1, "
                             "got %d" % slab)
        return min(slab, max(n, 1))
    rec = prod(shape[1:]) * np.dtype(dtype).itemsize
    return max(1, min(max(n, 1), _SLAB_BYTES // max(rec, 1)))


# ---------------------------------------------------------------------
# abstract stage interpretation (shared with bolt_tpu.analysis.check)
# ---------------------------------------------------------------------

def _stage_apply(stage, split, x):
    """Apply ONE device-side stage to traced value ``x`` — the same
    bodies the materialised paths compile, so streamed and materialised
    semantics cannot drift."""
    kind = stage[0]
    if kind == "map":
        from bolt_tpu.tpu.array import _chain_apply
        return _chain_apply((stage[1],), split, x)
    if kind == "chunk":
        from bolt_tpu.tpu.chunk import _general_map_body, _uniform_map_body
        _, func, plan, pad, canon = stage
        vshape = x.shape[split:]
        uniform = not any(pad) and all(
            v % c == 0 for v, c in zip(vshape, plan))
        if uniform:
            return _uniform_map_body(x, func, split, plan, canon)
        return _general_map_body(x, func, split, plan, pad, canon)
    if kind == "stack":
        from bolt_tpu.tpu.stack import _stack_map_body
        _, func, size, canon = stage
        return _stack_map_body(x, func, split, size, canon)
    if kind == "swap":
        # a swap stage is resolved by the two-phase shuffle executor
        # (resolve_swaps) BEFORE any slab program compiles — it can
        # never be applied slab-locally (the transpose crosses slab
        # boundaries), so reaching here is an internal routing bug
        raise RuntimeError(
            "internal: a 'swap' stage reached slab execution without "
            "being resolved — resolve_swaps must run first")
    raise ValueError("unknown stream stage %r" % (kind,))


def stage_label(stage):
    """Human label for one stage (analysis reports)."""
    def _name(f):
        return getattr(f, "__name__", None) or type(f).__name__
    kind = stage[0]
    if kind == "map":
        return "map(%s)" % _name(stage[1])
    if kind == "chunk":
        return "chunk(plan=%s).map(%s)" % (tuple(stage[2]), _name(stage[1]))
    if kind == "stack":
        return "stacked(%d).map(%s)" % (stage[2], _name(stage[1]))
    if kind == "filter":
        return "filter(%s)" % _name(stage[1])
    if kind == "swap":
        return "swap(perm=%s, split=%d)" % (stage[1], stage[2])
    return kind


def stage_aval(stage, split, aval):
    """Abstract result of one stage (``jax.eval_shape`` through the real
    bodies; memoised, ZERO XLA compiles)."""
    from bolt_tpu.tpu.array import _cached_eval_shape
    if stage[0] == "swap":
        # pure axis permutation: the abstract result needs no trace
        return jax.ShapeDtypeStruct(
            tuple(aval.shape[p] for p in stage[1]), aval.dtype)
    key = ("stream-stage", stage, split, tuple(aval.shape),
           str(aval.dtype))
    return _cached_eval_shape(
        key, lambda: jax.eval_shape(
            lambda d: _stage_apply(stage, split, d),
            jax.ShapeDtypeStruct(tuple(aval.shape), aval.dtype)))


class _ResultState:
    """What the stage chain produces: the static result aval (or the
    dynamic pre-filter bound), the result split, and the record count
    ``n``/value shape the terminals fold over."""

    __slots__ = ("shape", "dtype", "split", "dynamic", "n", "vshape",
                 "pred")

    def __init__(self, shape, dtype, split, dynamic, n, vshape, pred):
        self.shape = shape
        self.dtype = dtype
        self.split = split
        self.dynamic = dynamic
        self.n = n
        self.vshape = vshape
        self.pred = pred


def result_state(source):
    """Walk the stage chain abstractly (cached on the source)."""
    if source._state is not None:
        return source._state
    aval = jax.ShapeDtypeStruct(source.shape, source.dtype)
    split = source.split
    pred = None
    dynamic = False
    for stage in source.stages:
        if stage[0] == "filter":
            pred = stage[1]
            dynamic = True
            break                     # a filter is always the last stage
        aval = stage_aval(stage, split, aval)
        if stage[0] == "swap":
            split = stage[2]          # the swap re-draws the key|value cut
    n = prod(aval.shape[:split])
    vshape = tuple(aval.shape[split:])
    if dynamic:
        st = _ResultState(None, np.dtype(aval.dtype), 1, True, n, vshape,
                          pred)
    else:
        st = _ResultState(tuple(aval.shape), np.dtype(aval.dtype), split,
                          False, n, vshape, None)
    source._state = st
    return st


# ---------------------------------------------------------------------
# stage recording (called by the op layers on stream-backed arrays)
# ---------------------------------------------------------------------

def map_stage(arr, func):
    """Record a per-record map on a stream-backed array (lazy)."""
    from bolt_tpu.tpu.array import BoltArrayTPU
    return BoltArrayTPU._streamed(arr._stream.with_stage(("map", func)))


def filter_stage(arr, pred):
    """Record a trailing filter predicate (lazy, dynamic shape)."""
    from bolt_tpu.tpu.array import BoltArrayTPU
    return BoltArrayTPU._streamed(arr._stream.with_stage(("filter", pred)))


def chunked_map_stage(view, func, dtype):
    """Record a chunked per-block map on a streaming chunked view;
    returns the new view, or NotImplemented when the stage cannot be
    planned abstractly (the caller falls back to materialising)."""
    from bolt_tpu.tpu.array import BoltArrayTPU, _TRACE_ERRORS, _canon
    from bolt_tpu.tpu.chunk import ChunkedArray
    b = view._barray
    src = b._stream
    st = result_state(src)
    if st.dynamic:
        return NotImplemented
    plan = tuple(view._plan)
    pad = tuple(view._padding)
    canon = None if dtype is None else _canon(dtype)
    vshape = tuple(st.shape[st.split:])
    uniform = not any(pad) and all(
        v % c == 0 for v, c in zip(vshape, plan))
    stage = ("chunk", func, plan, pad, canon)
    try:
        nxt = stage_aval(stage, st.split,
                         jax.ShapeDtypeStruct(st.shape, st.dtype))
    except _TRACE_ERRORS:
        return NotImplemented       # the materialised path surfaces it
    except ValueError:
        raise                       # rank/block-shape contract violations
    if uniform:
        grid = tuple(v // c for v, c in zip(vshape, plan))
        new_plan = tuple(o // g for o, g in
                         zip(nxt.shape[st.split:], grid))
    else:
        new_plan = plan             # general path preserves blocks
    out = BoltArrayTPU._streamed(src.with_stage(stage))
    return ChunkedArray(out, new_plan, pad)


def stacked_map_stage(view, func, dtype):
    """Record a block-batched map on a streaming stacked view.

    Streams only when every slab holds a whole number of blocks
    (``records_per_slab % size == 0``): a stacked ``func`` may mix
    records WITHIN its block, so slab boundaries must align with block
    boundaries or streamed and materialised results would group records
    differently.  Misaligned geometries (and iterator sources, whose
    block sizes are not known up front) fall back to materialising."""
    from bolt_tpu.tpu.array import BoltArrayTPU, _TRACE_ERRORS, _canon
    from bolt_tpu.tpu.stack import StackedArray
    b = view._barray
    src = b._stream
    st = result_state(src)
    size = int(view._size)
    if st.dynamic or src.kind != "callback" or has_swap(src):
        # a pending swap re-draws the record axis, so the slab/block
        # alignment below would reason about the WRONG geometry —
        # materialise instead (rare: stacked maps over re-keyed streams)
        return NotImplemented
    if _multihost.mesh_process_count(src.mesh) > 1:
        # a stacked func mixes records WITHIN its block; per-process
        # shard boundaries would have to align with block boundaries on
        # every host — fall back to materialising rather than reason
        # about that geometry per process
        return NotImplemented
    recs_per_slab = src.slab * prod(st.shape[1:st.split])
    if recs_per_slab % size != 0:
        return NotImplemented
    canon = None if dtype is None else _canon(dtype)
    stage = ("stack", func, size, canon)
    try:
        stage_aval(stage, st.split,
                   jax.ShapeDtypeStruct(st.shape, st.dtype))
    except _TRACE_ERRORS:
        return NotImplemented
    out = BoltArrayTPU._streamed(src.with_stage(stage))
    return StackedArray(out, size)


def swap_stage(arr, perm, new_split):
    """Record a ``swap`` (axis re-keying) on a stream-backed array —
    LAZILY: the stage is a forecastable marker the two-phase shuffle
    executor (:func:`resolve_swaps`) resolves at consumption, so
    ``swap`` on a streamed source never materialises the input.
    Returns NotImplemented (→ the materialised path) when the swap
    cannot stream: a dynamic (post-filter) row count, a lossy ingest
    codec (phase 1 decodes once; a later terminal would quantise
    AGAIN, drifting from the materialised path), or a pod iterator
    source (per-process bucket ownership needs random access)."""
    from bolt_tpu.tpu.array import BoltArrayTPU
    src = arr._stream
    st = result_state(src)
    if st.dynamic:
        return NotImplemented
    codec_obj = resolve_codec(src)
    if codec_obj is not None and not codec_obj.lossless:
        return NotImplemented
    if _multihost.mesh_process_count(src.mesh) > 1 \
            and src.kind != "callback":
        return NotImplemented
    out = BoltArrayTPU._streamed(
        src.with_stage(("swap", tuple(int(p) for p in perm),
                        int(new_split))))
    return out


def has_swap(source):
    """Whether ``source`` carries an unresolved ``swap`` stage."""
    return any(s[0] == "swap" for s in source.stages)


def resolve_swaps(source):
    """Resolve every pending ``swap`` stage of ``source`` through the
    two-phase streaming shuffle (:func:`_resolve_one_swap`); returns a
    ``BoltArrayTPU`` — CONCRETE when the last resolution was resident
    (post-swap stages replayed through the normal materialised paths),
    STREAM-BACKED over spilled bucket files when it spilled (post-swap
    stages ride the new source lazily)."""
    b = _resolve_one_swap(source)
    while b._stream is not None and has_swap(b._stream):
        b = _resolve_one_swap(b._stream)
    return b


# ---------------------------------------------------------------------
# terminal routing
# ---------------------------------------------------------------------

_STAT_NAMES = ("sum", "mean", "var", "std")


def _swap_resolved(arr):
    """Resolve ``arr``'s pending swap stages IN PLACE (the adoption
    mirrors ``_data``'s adopt-after-success): returns the post-swap
    stream source to keep streaming over, or ``None`` when resolution
    landed a concrete array — the materialised paths own the rest."""
    res = resolve_swaps(arr._stream)
    arr._adopt_resolved(res)
    return arr._stream


def maybe_stat(arr, axis, name, keepdims, ddof):
    """Stream a reduction terminal when the geometry allows it; returns
    NotImplemented (→ the caller materialises) otherwise."""
    src = arr._stream
    if src is None or keepdims or name not in _STAT_NAMES:
        return NotImplemented
    if has_swap(src):
        # resolve the re-keying FIRST (two-phase shuffle): a resident
        # resolution lands concrete data (the materialised stat path
        # runs on it); a spilled one re-enters here over bucket files
        src = _swap_resolved(arr)
        if src is None:
            return NotImplemented
    st = result_state(src)
    if st.n == 0:
        return NotImplemented           # empty: materialised path's rules
    if axis is not None:
        from bolt_tpu.utils import tupleize
        if tuple(sorted(tupleize(axis))) != tuple(range(st.split)):
            return NotImplemented
    if name in ("mean", "var", "std") and np.issubdtype(
            st.dtype, np.complexfloating):
        return NotImplemented           # mirror the fused-filter gate
    return execute(arr, name, ddof=ddof)


def maybe_reduce(arr, func, axes, keepdims):
    """Stream a ``reduce(func)`` terminal when possible."""
    src = arr._stream
    if src is None or keepdims:
        return NotImplemented
    if has_swap(src):
        src = _swap_resolved(arr)     # see maybe_stat
        if src is None:
            return NotImplemented
    st = result_state(src)
    if st.pred is not None or st.n == 0:
        return NotImplemented
    if _multihost.mesh_process_count(src.mesh) > 1:
        # a user combine function has no mesh collective: the cross-host
        # fold cannot ride psum/pmin/pmax — materialise instead
        return NotImplemented
    if tuple(axes) != tuple(range(st.split)):
        return NotImplemented
    from bolt_tpu.tpu.array import _TRACE_ERRORS, _cached_eval_shape
    vaval = jax.ShapeDtypeStruct(st.vshape, st.dtype)
    try:
        _cached_eval_shape(
            ("reduce", func, st.vshape, str(vaval.dtype)),
            lambda: jax.eval_shape(func, vaval, vaval))
    except _TRACE_ERRORS:
        return NotImplemented           # host-fallback path resolves
    return execute(arr, "reduce", rfunc=func)


# ---------------------------------------------------------------------
# per-slab programs and on-device partial merges
# ---------------------------------------------------------------------

def _combine(terminal, rfunc, a, b, comps=None):
    """The ONE partial-merge arithmetic — traced by BOTH the standalone
    merge program (the pairwise tree above level 0) and the acc-fused
    slab program (level 0), so in-program and between-program merges
    cannot drift.  ``a`` is the EARLIER partial (fold order matters for
    ``reduce``); moments partials are ``(n, mu, M2)`` triples merged by
    the Chan et al. parallel recurrence (the statcounter ``mergeStats``
    formula, vectorised over the value block).  ``terminal="multi"``
    (the fused multi-stat accumulator, bolt_tpu/tpu/multistat.py) merges
    a TUPLE of components — each through this same function, so the
    fused tuple merge and the standalone merges share one arithmetic."""
    if terminal == "multi":
        return tuple(_combine(_COMP_MERGE[c], rfunc, x, y)
                     for c, x, y in zip(comps, a, b))
    if terminal == "sum":
        return jnp.add(a, b)
    if terminal == "min":
        return jnp.minimum(a, b)
    if terminal == "max":
        return jnp.maximum(a, b)
    if terminal == "reduce":
        return rfunc(a, b)
    n1, mu1, m21 = a
    n2, mu2, m22 = b
    n = n1 + n2
    safe = jnp.where(n > 0, n, jnp.asarray(1, n.dtype))
    delta = mu2 - mu1
    mu = mu1 + delta * (n2 / safe)
    m2 = m21 + m22 + delta * delta * (n1 * n2 / safe)
    return n, mu, m2


# multi-stat accumulator components -> the merge arithmetic each rides
# ("moments" is the statcounter (n, mu, M2) triple shared by every
# mean/var/std member of a fused group)
_COMP_MERGE = {"sum": "sum", "min": "min", "max": "max",
               "moments": "moments"}


def _terminal_partial(terminal, flat, mask, mfull, vshape, n, rfunc,
                      axes=None):
    """Per-slab partial for ONE terminal over the flattened records —
    the exact expressions the standalone slab programs have always
    traced, factored out so the fused multi-stat slab program composes
    the SAME arithmetic per component (streamed-fused vs streamed-
    standalone parity by construction).

    ``axes`` is the MULTI-PROCESS hook: inside a shard_map'd slab
    program ``flat`` is one device shard's records and ``axes`` names
    the mesh axes the slab's key axes shard over — the reduction points
    then insert the cross-host collective (``psum`` for sum and the
    moment components, ``pmin``/``pmax`` for order statistics), so the
    global partial leaves the program already combined across the pod:
    one collective per slab for sum/min/max, two for moments (the
    count+sum pair rides ONE fused psum; M2 needs the global mean
    first).  The arithmetic is the single-process expression applied
    hierarchically — sums of sums — so results match the one-process
    run exactly whenever the data keeps the reduction exact (even
    splits; the parity suite's contract)."""
    if terminal == "sum":
        # identity fold, exactly like _fused_filter_stat: dropped
        # records (NaNs included) become inert zeros
        v = flat if mfull is None else jnp.where(
            mfull, flat, jnp.asarray(0, flat.dtype))
        s = jnp.sum(v, axis=0)
        return jax.lax.psum(s, axes) if axes else s
    if terminal in ("min", "max"):
        # exact order statistics; a filter predicate never reaches here
        # (min/max multi-stat members are ineligible under a filter —
        # zero survivors would need the materialised error contract)
        op = jnp.min if terminal == "min" else jnp.max
        p = op(flat, axis=0)
        if axes:
            p = jax.lax.pmin(p, axes) if terminal == "min" \
                else jax.lax.pmax(p, axes)
        return p
    if terminal == "reduce":
        if axes:
            raise ValueError(
                "streamed reduce(func) cannot run on a multi-process "
                "mesh: a user combine function has no mesh collective")
        vfunc = jax.vmap(rfunc)
        y = flat
        while y.shape[0] > 1:
            half = y.shape[0] // 2
            combined = vfunc(y[:half], y[half:2 * half])
            if combined.shape != y[:half].shape:
                raise ValueError(
                    "reduce produced shape %s, expected value "
                    "shape %s" % (combined.shape[1:], tuple(vshape)))
            rem = y[2 * half:]
            y = jnp.concatenate([combined, rem], axis=0) \
                if rem.shape[0] else combined
        return y[0]
    # moments: the statcounter triple (n, mu, M2) per value slot
    out_dt = jax.eval_shape(
        lambda t: jnp.mean(t, axis=0),
        jax.ShapeDtypeStruct((1,) + tuple(vshape), flat.dtype)).dtype
    if mfull is None:
        cnt = jnp.asarray(n, out_dt)
        xf = flat.astype(out_dt)
    else:
        cnt = jnp.sum(mask.astype(out_dt))
        xf = jnp.where(mfull, flat,
                       jnp.asarray(0, flat.dtype)).astype(out_dt)
    sums = jnp.sum(xf, axis=0)
    if axes:
        # ONE fused collective for the pre-mean components: the global
        # count and per-slot sum land together
        cnt, sums = jax.lax.psum((cnt, sums), axes)
    safe = jnp.where(cnt > 0, cnt, jnp.asarray(1, out_dt))
    mu = sums / safe
    dev = xf - mu
    if mfull is not None:
        dev = jnp.where(mfull, dev, jnp.asarray(0, out_dt))
    m2 = jnp.sum(dev * dev, axis=0)
    if axes:
        m2 = jax.lax.psum(m2, axes)
    return cnt, mu, m2


def _slab_program(source, terminal, slab_shape, ddof, rfunc, fused=False,
                  comps=None, sharded=False, codec_obj=None):
    """The ONE compiled program each slab runs: device-side stages +
    (masked) terminal partial, with the slab buffer DONATED so the ring
    recycles its memory.  ``fused=True`` is the level-0 fold fusion: the
    program additionally takes the PREVIOUS slab's partial and merges it
    in the same dispatch (``prog(buf, acc)``), halving fold dispatches —
    the acc is donated too, it is consumed.  ``terminal="multi"`` emits
    a TUPLE of component partials (``comps`` ⊆ sum/moments/min/max) from
    the SAME single read of the slab — the streamed half of the fused
    multi-stat layer (bolt_tpu/tpu/multistat.py); each component traces
    the exact standalone expression via :func:`_terminal_partial`.

    ``codec_obj`` (ISSUE 14) is the ingest codec whose device-side
    DECODE is fused in as the program's FIRST traced expression: the
    uploaded buffer is the wire representation (plus sidecar leaves for
    sidecar codecs — the whole pytree is donated like the raw slab
    was), and the decoded values feed the exact same stage chain and
    terminal partial the uncompressed program traces — decode costs
    zero extra HBM passes.  With ``BOLT_CODEC_KERNEL=1`` an int8
    streamed ``sum`` with no stages routes through the Pallas
    decode-and-reduce kernel (``ops.kernels.fused_decode_sum``,
    geometry-gated, parity-locked) so the decode never leaves
    registers.

    ``sharded=True`` is the POD form (``parallel.multihost``): the same
    partial body runs under ``shard_map`` — each device computes its
    shard's partial and the reduction points carry the cross-host
    mesh-axis collective (see :func:`_terminal_partial`), so the
    program's output is the ALREADY-GLOBAL pair partial, replicated on
    every process (``out_specs=P()``).  The level-0 acc merge stays an
    elementwise combine on replicated values outside the shard_map —
    no extra collective; codec decode happens per shard INSIDE the
    shard_map (sidecar codecs are refused on pods before any thread
    starts).  Engine-cached per (stages, terminal, slab geometry,
    fused, comps, codec, process topology): uniform slabs compile
    exactly once per variant PER PROCESS."""
    stages = source.stages
    pred = None
    if stages and stages[-1][0] == "filter":
        pred = stages[-1][1]
        stages = stages[:-1]
    split = source.split
    mesh = source.mesh
    raw_dtype = source.dtype
    delta_ok = split < len(source.shape)
    use_kernel = (codec_obj is not None and codec_obj.name == "int8"
                  and terminal == "sum" and not stages and pred is None
                  and not sharded and split == 1
                  and _codec_registry().kernel_enabled())
    key = ("stream-slab-acc" if fused else "stream-slab", terminal,
           stages, pred, slab_shape, str(source.dtype), split, ddof,
           rfunc, comps, mesh,
           _multihost.topology_token() if sharded else None,
           codec_obj.name if codec_obj is not None else None,
           use_kernel)

    def build():
        axes = _multihost.key_collective_axes(mesh, slab_shape, split) \
            if sharded else None

        def partial(data):
            # under shard_map ``data`` is ONE device shard; standalone it
            # is the whole slab — the body is shape-polymorphic and the
            # collective points in _terminal_partial close the gap
            from bolt_tpu.tpu.array import _pred_mask
            if codec_obj is None:
                x = data
            else:
                if use_kernel:
                    # the opt-in in-register decode-and-reduce: plan
                    # resolution is static (shapes), so this branch is
                    # decided at trace time; off-plan geometries fall
                    # through to the XLA decode below
                    from bolt_tpu.ops.kernels import fused_decode_sum
                    out = fused_decode_sum(data[0], data[1], data[2])
                    if out is not None:
                        s = out.astype(raw_dtype)
                        return jax.lax.psum(s, axes) if axes else s
                if codec_obj.sidecar:
                    x = codec_obj.decode(data[0], data[1:], raw_dtype,
                                         delta_ok)
                else:
                    x = codec_obj.decode(data, (), raw_dtype, delta_ok)
            for stg in stages:
                x = _stage_apply(stg, split, x)
            vshape = x.shape[split:]
            n = prod(x.shape[:split])
            flat = x.reshape((n,) + vshape)
            mask = mfull = None
            if pred is not None:
                mask = _pred_mask(pred, flat)
                mfull = mask.reshape((n,) + (1,) * len(vshape))
            if terminal == "multi":
                return tuple(
                    _terminal_partial(c, flat, mask, mfull, vshape, n,
                                      None, axes=axes)
                    for c in comps)
            return _terminal_partial(
                terminal if terminal in ("sum", "reduce") else "moments",
                flat, mask, mfull, vshape, n, rfunc, axes=axes)

        if sharded:
            from jax.sharding import PartitionSpec
            from bolt_tpu import _compat
            from bolt_tpu.parallel.sharding import key_spec
            # check_vma=False: the outputs ARE replicated (every leaf
            # comes out of a psum/pmin/pmax over the sharding axes, and
            # shards along non-participating axes compute from identical
            # replicated inputs), but older runtimes' replication
            # checker cannot always prove it through the staged bodies
            body = _compat.shard_map(
                partial, mesh, in_specs=key_spec(mesh, slab_shape, split),
                out_specs=PartitionSpec(), check_vma=False)
        else:
            body = partial

        if not fused:
            return jax.jit(body, donate_argnums=(0,))

        def run(data, acc):
            # level-0 fold fused in: acc (the EVEN slab's partial) merges
            # with this (ODD) slab's partial inside one dispatch
            return _combine(terminal, rfunc, acc, body(data),
                            comps=comps)
        return jax.jit(run, donate_argnums=(0, 1))

    return _cached_jit(key, build)


def _merge_program(terminal, shape, dtype, rfunc, mesh):
    """On-device merge of two pair-partials — the tree above level 0
    (tiny, engine-cached, same :func:`_combine` arithmetic the fused
    slab program traces)."""
    if terminal in ("sum", "reduce"):
        key = ("stream-merge", terminal, rfunc, tuple(shape), str(dtype),
               mesh, _multihost.topology_token())

        def build():
            return jax.jit(lambda a, b: _combine(terminal, rfunc, a, b))
        return _cached_jit(key, build)

    key = ("stream-merge-moments", tuple(shape), str(dtype), mesh,
           _multihost.topology_token())

    def build():
        def merge(n1, mu1, m21, n2, mu2, m22):
            return _combine("moments", None, (n1, mu1, m21),
                            (n2, mu2, m22))
        return jax.jit(merge)
    return _cached_jit(key, build)


def _merge_multi_program(comps, sig, mesh):
    """Pairwise merge of two fused multi-stat partial TUPLES (pytree
    in, pytree out — one dispatch merges every component; ``sig`` is
    the flattened (shape, dtype) leaf signature for the cache key)."""
    key = ("stream-merge-multi", comps, sig, mesh,
           _multihost.topology_token())

    def build():
        return jax.jit(lambda a, b: _combine("multi", None, a, b,
                                             comps=comps))
    return _cached_jit(key, build)


def _finalise_program(terminal, shape, dtype, ddof, mesh):
    """Moments triple → the requested statistic (engine-cached)."""
    key = ("stream-final", terminal, tuple(shape), str(dtype), ddof, mesh,
           _multihost.topology_token())

    def build():
        nan = jnp.asarray(jnp.nan, dtype)
        dd = 0.0 if ddof is None else ddof

        def final(n, mu, m2):
            if terminal == "mean":
                return jnp.where(n > 0, mu, nan)
            var = jnp.where(n > 0, m2 / (n - jnp.asarray(dd, n.dtype)),
                            nan)
            if terminal == "std":
                return jnp.sqrt(var)
            return var
        return jax.jit(final)
    return _cached_jit(key, build)


class _PairFold:
    """Binary-counter pairwise tree over streamed PAIR partials (level-0
    merges are fused into the odd slab programs): leaf *i* merges at
    tree level ``trailing_zeros(i)``, so the fold depth is log2(nleaves)
    and no more than log2(n) partials are ever alive.  The merge program
    resolves LAZILY on the first actual merge — a 1- or 2-slab stream
    never builds (or counts) it."""

    __slots__ = ("_factory", "_merge", "levels")

    def __init__(self, merge_factory):
        self._factory = merge_factory
        self._merge = None
        self.levels = []

    def merge(self, a, b):
        if self._merge is None:
            self._merge = self._factory()
            self._factory = None        # hold nothing beyond the program
        return self._merge(a, b)

    def push(self, x):
        lvl = 0
        while lvl < len(self.levels) and self.levels[lvl] is not None:
            x = self.merge(self.levels[lvl], x)
            self.levels[lvl] = None
            lvl += 1
        if lvl == len(self.levels):
            self.levels.append(x)
        else:
            self.levels[lvl] = x

    def result(self):
        acc = None
        for x in self.levels:
            if x is None:
                continue
            acc = x if acc is None else self.merge(x, acc)
        return acc


def _make_fold(terminal, rfunc, comps, mesh, part):
    """A fresh :class:`_PairFold` for one run, its merge-program factory
    derived from a sample partial ``part`` — which may be a live device
    value (the first pushed pair) OR a host array restored from a
    checkpoint (the resume path rebuilds the fold around the persisted
    levels).  Captures only shape/dtype: a factory closing over the
    live partial would pin its device buffers for the whole run."""
    if terminal in ("sum", "reduce"):
        shape, dtype = part.shape, part.dtype
        return _PairFold(lambda: _merge_program(terminal, shape, dtype,
                                                rfunc, mesh))
    if terminal == "multi":
        sig = tuple((tuple(leaf.shape), str(leaf.dtype))
                    for leaf in jax.tree_util.tree_leaves(part))

        def factory():
            mp = _merge_multi_program(comps, sig, mesh)
            return lambda a, b: tuple(mp(a, b))
        return _PairFold(factory)
    mshape, mdtype = part[1].shape, part[1].dtype

    def factory():
        mp = _merge_program(terminal, mshape, mdtype, None, mesh)
        return lambda a, b: tuple(mp(*a, *b))
    return _PairFold(factory)


def _stage_token(stage):
    """One stage's fingerprint element: the kind, every callable by its
    BYTECODE token (``utils.code_token`` — two lambdas with different
    bodies differ, unlike ``__name__``), every plain value by repr."""
    from bolt_tpu.utils import code_token
    return "/".join(code_token(x) if callable(x) else repr(x)
                    for x in stage)


def _run_fingerprint(source, terminal, ddof, rfunc, specs, codec=None):
    """Identity of one LOGICAL streamed run for checkpoint matching:
    source geometry + slab plan + stage chain + terminal + ingest
    CODEC, with every user callable (stage funcs, the filter predicate,
    ``rfunc``, a callback source's ``produce``) identified by its
    bytecode digest — an EDITED pipeline over the same dir is refused,
    never resumed wrong, and a resumed run never adopts a checkpoint
    cut under a DIFFERENT codec (the fold partials are decoded values;
    mixing an uncompressed prefix with a quantised tail would be
    silently wrong, so a codec change restarts from scratch).  Closure
    DATA is not hashable (no checkpoint format's is): re-pointing an
    identical loader at different bytes of the same geometry is the
    caller's contract, as with any resume system."""
    from bolt_tpu.utils import code_token
    stages = "|".join(_stage_token(s) for s in source.stages)
    members = "|".join("%s:%s" % (n, d) for n, d in specs) if specs else ""
    return ("bolt-stream-ckpt-v2", str(terminal), str(ddof),
            code_token(rfunc) if rfunc is not None else "",
            "x".join(str(s) for s in source.shape),
            int(source.split), str(source.dtype), int(source.slab),
            str(source.kind),
            code_token(source.produce) if source.produce is not None
            else "", stages, members, str(codec or ""))


# ---------------------------------------------------------------------
# the executor
# ---------------------------------------------------------------------

# the most recent prefetch/dispenser thread and full pool
# (introspection for the fault tests)
_LAST_THREAD = None
_LAST_POOL = ()


class _Reseq:
    """Slab-order re-sequencing buffer between the uploader pool and the
    consumer: workers insert completed slabs by index, the consumer pops
    them STRICTLY in slab order — the fold stays deterministic and
    bit-exact no matter which upload finishes first.  Also the fault
    funnel: the first worker exception is recorded and re-raised in the
    consumer, and a liveness poll catches pool threads that died without
    delivering (the ``q.get()``-blocks-forever bug)."""

    __slots__ = ("_cond", "_slots", "_next", "_exc", "_total", "_fenced",
                 "_dead_err")

    def __init__(self):
        self._cond = _lockdep.condition("stream.reseq")
        self._slots = {}
        self._next = 0
        self._exc = None
        self._total = None
        self._fenced = 0
        self._dead_err = None

    def put(self, i, item):
        """Insert slab ``i``; returns False (dropping ``item``) for an
        index already handed to the consumer or already queued — the
        retry FENCE: a late duplicate from a slab's earlier attempt can
        never double-fold, whatever interleaving delivered it."""
        with self._cond:
            if i < self._next or i in self._slots:
                self._fenced += 1
                return False
            self._slots[i] = item
            self._cond.notify_all()
            return True

    @property
    def fenced(self):
        """Duplicate deliveries dropped by the fence."""
        with self._cond:
            return self._fenced

    def fault(self, exc):
        """Record the FIRST failure (later ones are consequences)."""
        with self._cond:
            if self._exc is None:
                self._exc = exc
            self._cond.notify_all()

    def finish(self, total):
        """All slabs dispensed: ``total`` is the slab count."""
        with self._cond:
            self._total = total
            self._cond.notify_all()

    def drain(self):
        """Release every queued ring buffer (abort path)."""
        with self._cond:
            self._slots.clear()

    def _dead(self, threads):
        """Pointed error naming the dead pool threads — the fix for the
        q.get()-blocks-forever bug.  Fires ONCE per dead thread set:
        each dead thread is named exactly once (a pool with 2 dead
        workers must not repeat the list), and repeated polls over the
        same set return the SAME error object, so a chained message
        cannot accumulate duplicates."""
        dead = [t for t in threads if not t.is_alive()] or threads
        key = tuple(sorted(t.ident or id(t) for t in dead))
        cached = self._dead_err
        if cached is not None and cached[0] == key:
            return cached[1]
        names = list(dict.fromkeys(repr(t.name) for t in dead))
        err = RuntimeError(
            "streaming prefetch thread(s) %s died without delivering "
            "slab %d or an error (thread killed before it could enqueue "
            "— e.g. interpreter teardown); the stream cannot complete"
            % (", ".join(names), self._next))
        self._dead_err = (key, err)
        return err

    def next(self, threads, workers=None, timeout=0.1, stall_limit=300,
             idle=None):
        """The next ``(slab_i, item)`` in slab order, or ``None`` at
        end-of-stream.  Re-raises a recorded pool fault; polls with a
        timeout and liveness checks so pool threads that died WITHOUT
        enqueueing (interpreter teardown, a killed thread) surface as a
        pointed error instead of blocking the consumer forever:

        * every INGESTING thread (``workers``, else all of ``threads``)
          dead with the needed slab undelivered → nothing can ever
          arrive, raise immediately (the dispenser alone cannot upload,
          so it blocking on ring permits must not mask dead workers);
        * the lead dispenser dead before announcing the slab count,
          workers alive but starved of jobs → raise after
          ``stall_limit`` polls with no new delivery (~30 s grace so a
          genuinely slow in-hand upload is not mistaken for the hang).

        ``idle`` (when given) runs OUTSIDE the lock after each poll that
        delivered nothing — the arbiter-backed runs' starvation valve:
        the consumer confirms already-retired in-flight windows there,
        releasing budget bytes the (possibly blocked) dispenser is
        waiting on, so a budget smaller than one run's full ring
        degrades to a shallower pipeline instead of a deadlock.
        """
        ingesters = threads if workers is None else workers
        lead = threads[0]
        stalls = 0
        seen = -1
        while True:
            with self._cond:
                # deliverable in-order slabs drain BEFORE a recorded
                # fault raises: they are complete uploads that fold
                # normally, and consuming them advances the resumable
                # checkpoint watermark to the true last retired slab —
                # the fault still re-raises on the first missing slab
                if self._next in self._slots:
                    i = self._next
                    self._next += 1
                    return i, self._slots.pop(i)
                if self._exc is not None:
                    raise self._exc
                if self._total is not None and self._next >= self._total:
                    return None
                if not any(t.is_alive() for t in ingesters):
                    raise self._dead(threads)
                if not lead.is_alive() and self._total is None:
                    # a delivery (even out-of-order) is progress: a
                    # worker finished an in-hand slab — reset the clock
                    if len(self._slots) != seen:
                        seen = len(self._slots)
                        stalls = 0
                    stalls += 1
                    if stalls > stall_limit:
                        raise self._dead(threads)
                self._cond.wait(timeout)
            if idle is not None:
                idle()


def _acquire(sem, stop):
    """Ring-permit acquire that gives up when the run is aborting (a
    pool thread must never deadlock on a dead main loop)."""
    while not stop.is_set():
        if sem.acquire(timeout=0.05):
            return True
    return False


def _pod_sync(x, pod, phase, slab=None):
    """``block_until_ready`` with the pod watchdog armed (ISSUE 11).

    Single-process (``pod=False``) this is a plain block.  On a pod the
    value may depend on a cross-host collective a DEAD peer will never
    complete: the watchdog first polls readiness
    (``podwatch.wait_ready`` — a latched dead peer raises the pointed
    ``PeerLostError`` instead of hanging this survivor in the runtime),
    then blocks for the value, classifying any transport failure
    (gloo connection closed — the fast shape of peer death) into the
    same ``PeerLostError`` via ``podwatch.reraise``."""
    if not pod:
        jax.block_until_ready(x)
        return
    _podwatch.wait_ready(x, phase=phase, slab=slab)
    try:
        jax.block_until_ready(x)
    except _podwatch.PeerLostError:
        raise
    except Exception as exc:          # noqa: BLE001 — classified
        _podwatch.reraise(exc, phase=phase, slab=slab)


def _multi_comps(specs):
    """Canonical component tuple for a fused multi-stat spec list —
    ONE 'moments' triple serves every mean/var/std member, 'min'/'max'
    serve their members AND both halves of a ``ptp``."""
    names = [name for name, _ in specs]
    comps = []
    if "sum" in names:
        comps.append("sum")
    if any(n in ("mean", "var", "std") for n in names):
        comps.append("moments")
    if "min" in names or "ptp" in names:
        comps.append("min")
    if "max" in names or "ptp" in names:
        comps.append("max")
    return tuple(comps)


def execute(arr, terminal, ddof=None, rfunc=None, specs=None,
            source=None):
    """Run a streamed reduction terminal over ``arr``'s source: the
    parallel-ingest, async-dispatch pipeline described in the module
    docstring.  Returns a value-shaped ``BoltArrayTPU`` (``split=0``).

    ``terminal="multi"`` streams a fused multi-stat group
    (bolt_tpu/tpu/multistat.py): ``specs`` is the ordered ``(name,
    ddof)`` member list, the per-slab program emits one component tuple
    per slab from a single read, and the return value is a LIST of
    value-shaped arrays, one per member — each finalised from the
    shared folded components exactly as its standalone streamed
    terminal would be.  ``source`` overrides ``arr._stream`` for
    callers resolving already-detached pending handles (``arr=None``
    skips the strict gate — the handle was gated at creation)."""
    global _LAST_THREAD, _LAST_POOL
    from bolt_tpu.tpu.array import BoltArrayTPU
    comps = _multi_comps(specs) if terminal == "multi" else None
    if source is None:
        source = arr._stream
    if arr is not None:
        _engine.strict_guard(arr, "stream.%s()" % terminal)
    if has_swap(source):
        # every terminal door resolves swaps before entering here; a
        # swap stage reaching the slab pipeline means a door was missed
        raise RuntimeError(
            "internal: execute() received a source with an unresolved "
            "swap stage — the terminal doors resolve swaps first "
            "(stream.resolve_swaps)")
    mesh = source.mesh
    split = source.split
    depth = prefetch_depth()
    nwork = pool_size(source)
    # codec-encoded ingest (ISSUE 14): resolved ONCE per run (scopes
    # are per-thread; the source's own codec= wins), validated against
    # the dtype (integer/bool pipelines refuse lossy codecs pointedly
    # in Codec.wire_dtype) and against the terminal: order statistics
    # are bit-exactness-sensitive, so lossy codecs refuse them.
    codec_obj = resolve_codec(source)
    if codec_obj is not None and not codec_obj.lossless:
        order = terminal in ("min", "max") or (
            terminal == "multi"
            and any(c in ("min", "max") for c in comps))
        if order:
            names = [n for n, _ in specs] if specs else [terminal]
            raise ValueError(
                "lossy codec %r refused for the order-statistic "
                "terminal(s) %s: min/max/ptp are exact by contract and "
                "a quantised extremum is never the answer the caller "
                "meant.  Use the lossless 'delta-f32' codec, or stream "
                "this terminal uncompressed" % (codec_obj.name, names))
    delta_ok = split < len(source.shape)
    wire_rec_bytes = prod(source.shape[1:]) * (
        codec_obj.wire_dtype(source.dtype).itemsize
        if codec_obj is not None else source.dtype.itemsize)
    # POD-SCALE run (parallel.multihost): the mesh spans processes, so
    # this executor instance is one of N peers running the SAME slab
    # schedule — each process produces and uploads only its own shard
    # of each slab (mspec.local_range), the slab programs are
    # shard_map'd with mesh-axis collectives doing the cross-host fold,
    # and every fold partial comes back replicated.  Slab order is
    # deterministic (the re-sequencer delivers strictly in order), so
    # every process enqueues the collective programs identically — the
    # rendezvous can never cross.
    mspec = None
    if _multihost.mesh_process_count(mesh) > 1:
        err = _multihost.slab_divisibility_error(
            mesh, source.shape, source.split,
            source.slab_ranges() if source.kind == "callback" else [])
        if err is not None:
            raise ValueError(err)       # BLT012 — check() forecasts it
        err = _multihost.sidecar_codec_error(codec_obj, mesh)
        if err is not None:
            raise ValueError(err)       # per-process sidecars cannot
            #                             feed a shard_map slab program
        mspec = _multihost.local_slab_spec(source)
    # multi-tenant serving (bolt_tpu.serve): the run charges its slab
    # bytes to the process-wide device-memory arbiter — the ring's local
    # permit bound still applies, but N concurrent tenants now share one
    # HBM budget instead of each assuming sole ownership.  The tenant
    # tag rides into the pool threads so their transfer accounting lands
    # in the submitting tenant's scoped counters.
    tenant_tag = _engine.current_tenant()
    lease = _tenant_lease()
    nretry = retry_limit()          # resolved HERE: scopes are per-thread
    # the arbiter leases COMPRESSED slab bytes: what actually occupies
    # the ring and crossed the link is the WIRE representation, so a
    # codec-encoded tenant's admission floor shrinks by the wire ratio
    # (analysis.admission_floor_bytes applies the same ratio)
    rec_bytes = wire_rec_bytes
    # resumable checkpointing (ISSUE 9): a per-source checkpoint dir
    # (fromcallback/fromiter checkpoint=) wins over the thread's
    # resumable() scope.  A matching checkpoint from a killed run is
    # loaded BEFORE any thread starts: the dispenser then skips the
    # already-retired slabs and the fold restarts from the persisted
    # accumulator — bit-identical, because the fold is a deterministic
    # function of (slab order, accumulator state) and both are exact.
    scope = checkpoint_scope()
    if source.ckpt is not None:
        ck_dir = source.ckpt
        ck_every = scope[1] if scope is not None else _CKPT_EVERY
    elif scope is not None:
        ck_dir, ck_every = scope
    else:
        ck_dir = ck_every = None
    start_slab = 0
    resume_records = 0
    ck_state = None
    ck_fp = None
    ck_remap = None
    if ck_dir is not None:
        from bolt_tpu import checkpoint as _ckptlib
        if mspec is not None and \
                _multihost.mesh_process_count(mesh) \
                != _multihost.process_count():
            # the checkpoint rendezvous (multihost.barrier) is a
            # collective over the WHOLE runtime; a mesh spanning only a
            # subset of the pod's processes would leave non-participants
            # out of the barrier and hang the participants forever —
            # refuse pointedly instead
            raise ValueError(
                "resumable checkpointing on a SUB-POD mesh is not "
                "supported: this mesh spans %d of the runtime's %d "
                "processes, and the checkpoint rendezvous barrier "
                "covers the whole runtime.  Stream the checkpointed "
                "run on a mesh covering every process (or drop "
                "checkpoint=/resumable() for this sub-mesh run)"
                % (_multihost.mesh_process_count(mesh),
                   _multihost.process_count()))
        ck_fp = _run_fingerprint(
            source, terminal, ddof, rfunc, specs,
            codec=codec_obj.name if codec_obj is not None else None)
        # the MESH's multiprocess answer, not the runtime's: a
        # process-local mesh inside a multi-process runtime checkpoints
        # single-process (its peers are elsewhere; a barrier would hang)
        ck_info = {}
        got_ck = _ckptlib.stream_load(ck_dir, ck_fp,
                                      multiprocess=mspec is not None,
                                      info=ck_info)
        if got_ck is not None:
            start_slab, resume_records, ck_state = got_ck
            # topology remap (shrink-and-resume): the checkpoint was cut
            # by a different pod width; the adopted state is the
            # replicated global fold, and the remap is recorded in every
            # subsequent checkpoint this run writes
            ck_remap = ck_info.get("remapped_from")
            _engine.record_stream_resume()
            _obs.event("stream.resume", slabs=start_slab,
                       records=resume_records,
                       **({"remapped_from": ck_remap}
                          if ck_remap is not None else {}))
    ranges = source.slab_ranges()[start_slab:] \
        if source.kind == "callback" else None
    total_slabs = len(ranges) if ranges is not None else None
    # the donated ring: at most depth + pool-size slab buffers exist at
    # once (each worker holds one in hand, depth more may wait uploaded
    # or dispatched-unconfirmed).  A permit is acquired per dispensed
    # slab and released when the consumer CONFIRMS its program retired
    # (the in-flight window sync) — so ring memory stays capped even
    # though dispatch is async.
    ring = depth + nwork
    window = ring - 1          # one slot always free for the dispenser
    permits = threading.Semaphore(ring)
    stop = threading.Event()
    rsq = _Reseq()
    # concurrent-uploader accounting (the parallel-ingest proof in the
    # engine counters: stream_upload_threads records the high-water)
    act_lock = _lockdep.lock("stream.uploader_hw")
    act = {"n": 0, "hw": 0}

    def _act_enter():
        with act_lock:
            act["n"] += 1
            if act["n"] > act["hw"]:
                act["hw"] = act["n"]

    def _act_exit():
        with act_lock:
            act["n"] -= 1

    # spans the pool threads begin parent under THIS run's span by
    # explicit handoff (thread-local nesting does not cross threads):
    # the exported timeline then shows ingest slabs under the run that
    # caused them, overlapping the main thread's compute slabs
    run_sp = _obs.begin("stream.run", terminal=terminal, depth=depth,
                        uploaders=nwork, kind=source.kind,
                        **({"codec": codec_obj.name}
                           if codec_obj is not None else {}))

    jobq = queue.Queue()

    def _encode_upload(block, slab_shape, axis0_off):
        """Encode (when a codec is armed) + upload ONE host block;
        returns ``(buf, wire_nbytes)``.  ``buf`` is the bare sharded
        wire/raw array, or — for sidecar codecs — a ``(wire, *sidecar)``
        tuple whose every leaf the slab program donates.  The wire
        block keeps the raw block's SHAPE (codecs change only the
        dtype), so the per-device placement math is untouched."""
        side = ()
        if codec_obj is None:
            payload = block
        else:
            payload, side = _encode_slab(codec_obj, block, delta_ok)
        if mspec is None:
            # through the module-level name so the single-process
            # upload seam stays patchable (the fault/ordering tests'
            # contract)
            buf = _upload_slab(payload, mesh, split)
        else:
            buf = _upload_slab_mh(payload, mesh, split, slab_shape,
                                  axis0_off)
        if side:
            # tiny per-slab sidecar (int8's scale/zero point): counted
            # honest through the ONE transfer door like everything else
            buf = (buf,) + tuple(transfer(np.asarray(s)) for s in side)
        return buf, int(payload.nbytes)

    def dispenser():
        """Callback sources: hand (slab_i, lo, hi) index jobs to the
        uploader pool in slab order; workers produce AND upload their
        own slabs concurrently (random access makes that safe).  Ring
        permits AND arbiter bytes are acquired HERE, in slab order —
        per-stream in-order budget delivery, so a tenant's own slabs can
        never deadlock each other by acquiring out of order."""
        try:
            i = 0
            for lo, hi in ranges:
                if not _acquire(permits, stop):
                    return
                if lease is not None:
                    nrec = hi - lo
                    if mspec is not None:
                        llo, lhi = mspec.local_range(lo, hi)
                        nrec = lhi - llo    # this process uploads only
                        #                     its own shard's bytes
                    if not lease.acquire(nrec * rec_bytes, stop=stop):
                        return
                jobq.put((i, lo, hi))
                i += 1
            rsq.finish(i)
        except BaseException as exc:        # noqa: BLE001 — re-raised in
            rsq.fault(exc)                  # the consumer thread
        finally:
            for _ in range(nwork):
                jobq.put(None)              # poison pills: pool drains

    def _retry_or_raise(i, attempt, prev, exc):
        """One failed ingest attempt: burn a retry (record + chain the
        attempt's exception) or raise the run-poisoning final error —
        the chaining policy itself is the shared
        ``utils.chain_retry_step`` (one policy for stream AND serve).
        At budget 0 the ORIGINAL exception propagates untouched — the
        historical fail-fast contract."""
        from bolt_tpu.utils import chain_retry_step
        allowed = attempt < nretry and not stop.is_set()
        if allowed:
            _engine.record_stream_retry()
            _obs.event("stream.retry", slab=start_slab + i,
                       attempt=attempt + 1, error=type(exc).__name__)
        return chain_retry_step(
            exc, prev, attempt, allowed, "slab %d" % (start_slab + i),
            "stream.retries / BOLT_STREAM_RETRIES")

    def worker(wid):
        try:
            with _engine.tenant(tenant_tag):
                while True:
                    job = jobq.get()
                    if job is None or stop.is_set():
                        return
                    i, lo, hi = job
                    attempt = 0
                    prev = None
                    while True:
                        _act_enter()
                        sp = _obs.begin("stream.ingest", parent=run_sp,
                                        slab=start_slab + i, worker=wid,
                                        attempt=attempt)
                        t0 = _clock()
                        try:
                            if mspec is None:
                                block = source.produce_slab(lo, hi)
                                buf, bnb = _encode_upload(
                                    block, block.shape, 0)
                            else:
                                # per-process ingest contract: produce
                                # and upload ONLY this host's shard of
                                # the slab (global coordinates); with a
                                # codec armed the LOCAL shard encodes,
                                # so DCN/gloo ingest bytes shrink too
                                llo, lhi = mspec.local_range(lo, hi)
                                block = source.produce_slab(llo, lhi)
                                buf, bnb = _encode_upload(
                                    block, mspec.slab_shape(lo, hi),
                                    llo - lo)
                            tsec = _clock() - t0
                            if sp is not None:
                                sp.set(bytes=bnb, lo=lo, hi=hi)
                        except BaseException as exc:  # noqa: BLE001
                            _obs.end(sp, error=type(exc).__name__)
                            _act_exit()
                            # retry IN PLACE on this worker (the job
                            # keeps its ring permit and arbiter bytes);
                            # the re-sequencer fences any duplicate
                            prev = _retry_or_raise(i, attempt, prev, exc)
                            attempt += 1
                            continue
                        _obs.end(sp)
                        _act_exit()
                        break
                    del block          # bnb = the LOCAL WIRE bytes this
                    #                    process acquired and uploaded
                    rsq.put(i, (buf, bnb, tsec, hi))
        except BaseException as exc:        # noqa: BLE001 — re-raised in
            rsq.fault(exc)                  # the consumer thread

    def prefetch():
        """Iterator sources: ONE produce+upload thread (the iterable is
        sequential; concurrent ``next()`` would corrupt it).  The ingest
        span/time covers produce AND upload, like a worker's; arbiter
        bytes are acquired between produce and upload (an iterator
        slab's size is only known once the block is in hand)."""
        i = 0
        try:
            with _engine.tenant(tenant_tag):
                it = source.slabs()
                if start_slab:
                    # resume: drain the already-retired prefix, checking
                    # the block layout still cuts at the checkpointed
                    # record (a drifted iterator would silently corrupt
                    # the fold — refuse instead)
                    skipped_hi = 0
                    for k in range(start_slab):
                        try:
                            _, skipped_hi, blk = next(it)
                        except StopIteration:
                            raise RuntimeError(
                                "resume checkpoint covers %d slabs but "
                                "this iterator ended after %d; the "
                                "source is not the one the checkpoint "
                                "was cut from" % (start_slab, k))
                        del blk
                    if skipped_hi != resume_records:
                        raise RuntimeError(
                            "resume checkpoint was cut at record %d but "
                            "this iterator's first %d slab(s) cover %d "
                            "records — the block layout drifted; delete "
                            "the checkpoint or restore the original "
                            "source" % (resume_records, start_slab,
                                        skipped_hi))
                while True:
                    if stop.is_set():
                        return
                    if not _acquire(permits, stop):
                        return
                    _act_enter()
                    sp = _obs.begin("stream.ingest", parent=run_sp,
                                    slab=start_slab + i)
                    t0 = _clock()
                    try:
                        try:
                            lo, hi, block = next(it)
                        except StopIteration:
                            _obs.cancel(sp)   # probe saw end-of-source
                            sp = None
                            permits.release()  # unused hand-slot permit
                            break
                        axis0_off = 0
                        if mspec is not None:
                            # per-process contract for iterator sources:
                            # every process walks the SAME re-iterable
                            # block sequence and uploads only its shard
                            # slice of each global block (validated per
                            # block — an indivisible slab raises the
                            # pointed BLT012 error here)
                            llo, lhi = mspec.local_range(lo, hi)
                            axis0_off = llo - lo
                            block = block[llo - lo:lhi - lo]
                        # acquire the WIRE bytes (exact: codecs keep the
                        # raw shape, only the itemsize changes) — the
                        # arbiter budgets what will actually occupy the
                        # ring, and the release below mirrors it
                        want = (int(block.size)
                                * codec_obj.wire_dtype(
                                    source.dtype).itemsize
                                if codec_obj is not None
                                else int(block.nbytes))
                        if lease is not None and not lease.acquire(
                                want, stop=stop):
                            return
                        attempt = 0
                        prev = None
                        while True:
                            try:
                                buf, bnb = _encode_upload(
                                    block,
                                    block.shape if mspec is None
                                    else mspec.slab_shape(lo, hi),
                                    axis0_off)
                                break
                            except BaseException as exc:  # noqa: BLE001
                                # the block is in hand (an iterator
                                # cannot re-produce it), so the retry
                                # budget covers the ENCODE + UPLOAD here
                                prev = _retry_or_raise(i, attempt, prev,
                                                       exc)
                                attempt += 1
                        tsec = _clock() - t0
                        if sp is not None:
                            sp.set(bytes=bnb, lo=lo, hi=hi)
                    finally:
                        _obs.end(sp)
                        _act_exit()
                    del block
                    rsq.put(i, (buf, bnb, tsec, hi))
                    i += 1
                rsq.finish(i)
        except BaseException as exc:        # noqa: BLE001
            rsq.fault(exc)

    if source.kind == "callback":
        lead = threading.Thread(target=dispenser,
                                name="bolt-stream-prefetch", daemon=True)
        pool = [threading.Thread(target=worker, args=(w,),
                                 name="bolt-stream-upload-%d" % w,
                                 daemon=True)
                for w in range(nwork)]
        threads = [lead] + pool
        ingesters = pool               # only workers deliver slabs
    else:
        lead = threading.Thread(target=prefetch,
                                name="bolt-stream-prefetch", daemon=True)
        threads = [lead]
        ingesters = threads
    _LAST_THREAD = lead
    _LAST_POOL = tuple(threads)

    t_start = _clock()
    ingest = 0.0
    compute = 0.0
    nslabs = 0
    fold = None
    pend = None                 # even slab's partial awaiting its pair
    pend_bytes = 0              # that slab's arbiter bytes, still held
    pending_sync = deque()      # (slabs covered, partial, bytes) not
    #                             yet confirmed retired
    dispatched = 0
    confirmed = 0
    inflight_hw = 0
    done_records = resume_records   # records covered by retired slabs
    if ck_state is not None:
        # restore the EXACT fold state the checkpoint captured: the
        # pairwise-tree levels and the unpaired pair partial, as host
        # arrays — the merge/fused programs accept them directly (the
        # arithmetic is placement-independent, so the resumed result
        # stays bit-identical to the uninterrupted run)
        lv, pend = ck_state
        sample = next((x for x in lv if x is not None), pend)
        if sample is not None:
            fold = _make_fold(terminal, rfunc, comps, mesh, sample)
            fold.levels = list(lv)

    def _confirm_oldest():
        """Sync the OLDEST unconfirmed pair partial (normally long
        retired, ~free) and release its ring permits + arbiter bytes.
        On a pod the sync rides the watchdog: a partial whose
        collective a dead peer will never complete raises the pointed
        PeerLostError instead of hanging this survivor."""
        nonlocal compute, confirmed
        cov, ref, nb = pending_sync.popleft()
        ssp = _obs.begin("stream.sync", slabs=cov)
        t0 = _clock()
        try:
            _pod_sync(ref, mspec is not None, "slab-partial sync")
        finally:
            _obs.end(ssp)
        compute += _clock() - t0
        confirmed += cov
        permits.release(cov)
        if lease is not None:
            lease.release(nb)

    def _starved():
        """The arbiter-backed starvation valve (rsq.next's ``idle``):
        with the feeder possibly blocked on budget bytes, confirm one
        retired window per empty poll so its bytes recycle — a budget
        smaller than the full ring then runs a shallower pipeline
        instead of deadlocking.  Opens ONLY under real arbiter
        contention (some acquire is queued — this run's blocked feeder
        always is one): a feeder merely slow on I/O must not collapse
        the bounded in-flight window into per-slab syncs.  The lone
        unpaired partial is drained too: once its slab program retires,
        the donated slab input is recycled and only a value-shaped
        partial lives, so holding its slab-sized bytes would starve the
        feeder forever on a one-slab-at-a-time budget."""
        nonlocal pend_bytes
        if lease.arbiter.waiting() == 0:
            return                  # nobody needs bytes: keep the window
        if pending_sync:
            _confirm_oldest()
        elif pend is not None and pend_bytes:
            _pod_sync(pend, mspec is not None, "unpaired-partial sync")
            lease.release(pend_bytes)
            pend_bytes = 0

    def _fold_push(part):
        # pair-partials fold as a PAIRWISE tree for every terminal —
        # the moments merge included, so power-of-two slab counts keep
        # the Chan denominators exact (level 0 is fused into the odd
        # slab programs; this tree is level 1 and up)
        nonlocal fold
        if fold is None:
            fold = _make_fold(terminal, rfunc, comps, mesh, part)
        fold.push(part)

    def _write_checkpoint(abort=False):
        """Persist the retired-slab watermark + fold state: drain the
        async window first (permits and arbiter bytes release — the
        persisted state must cover exactly the retired slabs), pull the
        value-shaped partials to host, write atomically.

        ``abort=True`` is the failure-path write.  On a POD it skips
        the rendezvous barriers (peers may be dead or at other
        watermarks) and the meta advances only forward
        (``stream_save(rendezvous=False)``); the drain above still
        runs WATCHDOG-guarded, so the write only lands when every
        retired slab's collective actually completed — i.e. exactly
        when the abort watermark is rendezvous-consistent.  A partial
        hung on the dead peer raises PeerLostError out of the drain
        and the caller falls back to the last periodic checkpoint."""
        while pending_sync:
            _confirm_oldest()
        state = (list(fold.levels) if fold is not None else [], pend)
        csp = _obs.begin("stream.checkpoint",
                         slabs=start_slab + nslabs)
        t0 = _clock()
        try:
            _pod_sync(state, mspec is not None, "checkpoint drain")
            nb = _ckptlib.stream_save(ck_dir, ck_fp, start_slab + nslabs,
                                      done_records, state,
                                      multiprocess=mspec is not None,
                                      rendezvous=not (abort
                                                      and mspec
                                                      is not None),
                                      remap_from=ck_remap,
                                      codec=codec_obj.name
                                      if codec_obj is not None else None)
            _engine.record_checkpoint(nb, _clock() - t0)
            if csp is not None:
                csp.set(bytes=nb)
        finally:
            _obs.end(csp)

    for th in threads:
        th.start()
    if mspec is not None:
        # the supervisor must not reform the pod UP under a live
        # collective schedule — this counter is what its quiesce
        # drain waits on (bolt_tpu.parallel.supervisor)
        _podwatch.pod_enter()
    ready_done = False
    try:
        try:
            while True:
                got = rsq.next(threads, workers=ingesters,
                               idle=_starved if lease is not None
                               else None)
                if got is None:
                    break
                if mspec is not None and not ready_done:
                    # pre-collective readiness rendezvous (ISSUE 12):
                    # confirm every peer is alive over the heartbeat
                    # transport BEFORE the first dispatch enters the
                    # runtime — a peer that died before dispatching
                    # raises the pointed PeerLostError within ~2x
                    # BOLT_POD_TIMEOUT instead of this survivor
                    # blocking ~30s in gloo's connect
                    _podwatch.ready_rendezvous()
                    ready_done = True
                slab_i, (buf, slab_bytes, tsec, slab_hi) = got
                # slab_bytes is the PROCESS-LOCAL upload size the worker
                # acquired from the arbiter (== buf.nbytes single-process;
                # this process's shard of it on a pod) — releases must
                # mirror acquires or the serve budget drifts
                ingest += tsec
                t0 = _clock()
                wshape = (buf[0].shape if isinstance(buf, tuple)
                          else buf.shape)
                csp = _obs.begin("stream.compute",
                                 slab=start_slab + slab_i,
                                 **({"codec": codec_obj.name}
                                    if codec_obj is not None else {}))
                _chaos.hit("stream.dispatch")
                if mspec is not None:
                    # the pod collective seam: this dispatch enqueues a
                    # cross-host rendezvous on every process
                    _chaos.hit("multihost.collective")
                try:
                    with warnings.catch_warnings():
                        # backends without donation (the CPU dev mesh)
                        # warn that the donated slab buffer was unusable
                        # — expected there, and pure noise once per slab
                        # geometry
                        warnings.filterwarnings(
                            "ignore",
                            message="Some donated buffers were not usable")
                        try:
                            # with a codec armed the dispatch IS the
                            # fused on-device decode — surfaced on the
                            # timeline as a stream.decode span nested
                            # in this slab's stream.compute (ended in
                            # the finally so a faulting dispatch never
                            # leaks it)
                            dsp = (_obs.begin("stream.decode",
                                              codec=codec_obj.name,
                                              slab=start_slab + slab_i)
                                   if codec_obj is not None else None)
                            try:
                                if pend is None:
                                    prog = _slab_program(
                                        source, terminal, wshape, ddof,
                                        rfunc, comps=comps,
                                        sharded=mspec is not None,
                                        codec_obj=codec_obj)
                                    pend = prog(buf)
                                    pend_bytes = slab_bytes
                                    pairp = None
                                else:
                                    # level-0 fold fused in
                                    prog = _slab_program(
                                        source, terminal, wshape, ddof,
                                        rfunc, fused=True, comps=comps,
                                        sharded=mspec is not None,
                                        codec_obj=codec_obj)
                                    pairp = prog(buf, pend)
                            finally:
                                _obs.end(dsp)
                            if pairp is not None:
                                pend = None
                                _fold_push(pairp)
                                pending_sync.append(
                                    (2, pairp, pend_bytes + slab_bytes))
                                pend_bytes = 0
                        except _podwatch.PeerLostError:
                            raise
                        except Exception as exc:  # noqa: BLE001
                            if mspec is None:
                                raise
                            # a dead peer fails the collective FAST on
                            # localhost TCP (gloo closes the socket) —
                            # classify into the pointed PeerLostError
                            # naming the peer and the in-flight slab
                            _podwatch.reraise(exc, phase="slab program",
                                              slab=start_slab + slab_i)
                    # counted INSIDE the try, right after the fold state
                    # absorbed the slab: the abort-path checkpoint below
                    # keys its watermark off nslabs, and a watermark
                    # lagging the state would double-fold on resume
                    nslabs += 1
                    done_records = slab_hi
                    del buf, got           # the donated ring slot is free
                finally:
                    _obs.end(csp)
                compute += _clock() - t0
                dispatched += 1
                if dispatched - confirmed > inflight_hw:
                    inflight_hw = dispatched - confirmed
                # bounded in-flight window: NO per-slab sync — only once
                # the window fills does the consumer block, and then on
                # the OLDEST pair partial, dispatched ~window slabs ago
                # and normally long retired (a ~free wait that releases
                # its ring permits and arbiter bytes)
                while dispatched - confirmed > window and pending_sync:
                    _confirm_oldest()
                # resumable(): persist the fold state every ck_every
                # retired slabs (skipping the final slab of a known-size
                # stream — the run is about to finish and clear anyway)
                if ck_dir is not None and nslabs % ck_every == 0 \
                        and not (total_slabs is not None
                                 and nslabs >= total_slabs):
                    if mspec is not None:
                        # the slab-boundary QUIESCE gate (ISSUE 12): a
                        # supervisor folding a rejoined process back in
                        # asks running pod streams to stop HERE — the
                        # checkpoint just written is the resume point.
                        # Process 0 publishes its decision BEFORE the
                        # checkpoint, whose own rendezvous barriers
                        # fence the marker read, so every peer abandons
                        # the same watermark (PodQuiesceError,
                        # retryable like a peer loss) with no second
                        # standalone barrier per checkpoint
                        _podwatch.quiesce_pre(start_slab + nslabs)
                    _write_checkpoint()
                    if mspec is not None:
                        _podwatch.quiesce_gate(start_slab + nslabs,
                                               fenced=True)
            if pend is not None:
                # odd slab count: the unpaired tail partial joins the
                # tree as its own leaf (deterministic — slab order only)
                _fold_push(pend)
                pend = None
        except BaseException:
            # the run is failing (uploader death, source error, peer
            # loss, a chaos-injected fault): persist the retired-slab
            # watermark FIRST, so the next run over this source resumes
            # from here instead of from the last periodic checkpoint —
            # best effort, never masking the original exception.  On a
            # POD the abort write skips the rendezvous (peers may be
            # dead) and lands only when the watchdog-guarded drain
            # proves every retired slab's collective completed — the
            # abort watermark is then rendezvous-consistent by
            # construction, and the fold partials are replicated
            # global values any surviving process can resume from
            # (stream_save(rendezvous=False); the PR 9 carve-out that
            # skipped pods entirely is gone).
            if ck_dir is not None and nslabs:
                try:
                    _write_checkpoint(abort=True)
                except Exception:       # noqa: BLE001 — the original
                    pass                # failure is the story (a drain
                #                         hung on the dead peer falls
                #                         back to the last periodic
                #                         checkpoint)
            raise
        finally:
            stop.set()
            # the consumer's OWN poison pills: if the dispenser was
            # killed before its finally could enqueue them, workers sit
            # blocked in jobq.get() forever and the joins below would
            # reproduce the very hang the liveness guard reports —
            # extra pills are harmless (workers exit on the first one)
            for _ in range(len(threads)):
                jobq.put(None)
            for th in threads:
                th.join()
            rsq.drain()                   # release queued ring buffers
            pending_sync.clear()

        if fold is None:
            raise RuntimeError(
                "stream produced no slabs (empty source?) — nothing to "
                "reduce; the materialised path owns empty-input rules")
        _chaos.hit("stream.fold")
        fsp = _obs.begin("stream.fold", final=True)
        t0 = _clock()
        try:
            if terminal in ("sum", "reduce"):
                out = fold.result()
            elif terminal == "multi":
                out = _finalise_multi(fold.result(), comps, specs, mesh)
            else:
                n, mu, m2 = fold.result()
                out = _finalise_program(terminal, mu.shape, mu.dtype,
                                        ddof, mesh)(n, mu, m2)
            # the ONE synchronisation point of the whole run (pod runs
            # sync through the watchdog: a tail collective hung on a
            # dead peer raises PeerLostError, never an infinite wait)
            _pod_sync(out, mspec is not None, "final result sync")
        except BaseException:
            # same abort-watermark contract as the main loop: the fold
            # state covers every retired slab, so a failure here still
            # leaves the best possible resume point
            if ck_dir is not None and nslabs:
                try:
                    _write_checkpoint(abort=True)
                except Exception:       # noqa: BLE001
                    pass
            raise
        finally:
            _obs.end(fsp)
        if ck_dir is not None:
            # success: a finished run leaves NO stale checkpoint behind
            _ckptlib.stream_clear(ck_dir, multiprocess=mspec is not None)
        compute += _clock() - t0
        wall = _clock() - t_start
        overlap = max(0.0, ingest + compute - wall)
        _engine.record_stream(nslabs, ingest, compute, wall, overlap,
                              depth, uploaders=max(act["hw"], 1),
                              inflight=max(inflight_hw, 1))
        if run_sp is not None:
            run_sp.set(slabs=nslabs, ingest_s=round(ingest, 6),
                       compute_s=round(compute, 6),
                       overlap_s=round(overlap, 6),
                       concurrent_uploaders=max(act["hw"], 1),
                       inflight_high_water=max(inflight_hw, 1))
        if terminal == "multi":
            return list(out)              # one jax array per member spec
        return BoltArrayTPU(out, 0, mesh)
    finally:
        if mspec is not None:
            _podwatch.pod_exit()
        if lease is not None:
            lease.close()       # return every outstanding budget byte
        _obs.end(run_sp)


def _finalise_multi(folded, comps, specs, mesh):
    """Per-member outputs from the folded component tuple: each member
    finalises from the SHARED components exactly as its standalone
    streamed terminal would (``_finalise_program`` for the moment
    family, identity for sum/min/max, the fused max−min subtraction for
    ``ptp``)."""
    by = dict(zip(comps, folded))

    def _sub(a, b):
        # the SAME cached max−min program the in-memory fused groups
        # use (one "multi-stat-sub" key per geometry, both paths)
        from bolt_tpu.tpu.multistat import _sub_program
        return _sub_program(a.shape, a.dtype, mesh)(a, b)

    outs = []
    for name, ddof_m in specs:
        if name == "sum":
            outs.append(by["sum"])
        elif name == "min":
            outs.append(by["min"])
        elif name == "max":
            outs.append(by["max"])
        elif name == "ptp":
            outs.append(_sub(by["max"], by["min"]))
        else:
            n, mu, m2 = by["moments"]
            outs.append(_finalise_program(name, mu.shape, mu.dtype,
                                          ddof_m, mesh)(n, mu, m2))
    return outs


# ---------------------------------------------------------------------
# materialisation (the fallback for non-streaming consumers)
# ---------------------------------------------------------------------

def materialize(source):
    """Build the CONCRETE array a stream source describes, by the
    standard machinery: the base uploads whole (per device shard for
    callback sources, host-assembled for iterator sources), then every
    recorded stage replays through the normal deferred/chunked/stacked
    paths — so a materialised stream is bit-identical to having never
    streamed at all.  Needs the full array to fit; streaming terminals
    exist so it usually never runs."""
    with _obs.span("stream.materialize", kind=source.kind,
                   stages=len(source.stages)):
        return _materialize_spans(source)


def _materialize_spans(source):
    if has_swap(source):
        # the two-phase shuffle resolves the re-keying SLAB-WISE (the
        # input never lives whole next to the output); a resident
        # resolution is already the concrete replayed array, a spilled
        # one materialises from its bucket files
        b = resolve_swaps(source)
        if b._stream is None:
            return b
        source = b._stream
    b = _materialize_base(source)
    return _replay_stages(b, source.stages)


def _replay_stages(b, stages):
    """Replay recorded stream stages on a CONCRETE array through the
    normal deferred/chunked/stacked/swap paths — the ONE replay used by
    materialisation AND the resident shuffle's post-swap tail, so both
    are bit-identical to having never streamed at all."""
    for stage in stages:
        kind = stage[0]
        if kind == "map":
            b = b.map(stage[1], axis=tuple(range(b.split)))
        elif kind == "chunk":
            from bolt_tpu.tpu.chunk import ChunkedArray
            _, func, plan, pad, canon = stage
            b = ChunkedArray(b, plan, pad).map(func, dtype=canon).unchunk()
        elif kind == "stack":
            from bolt_tpu.tpu.stack import StackedArray
            _, func, size, canon = stage
            b = StackedArray(b, size).map(func, dtype=canon).unstack()
        elif kind == "filter":
            b = b.filter(stage[1], axis=tuple(range(b.split)))
        elif kind == "swap":
            b = _replay_swap(b, stage[1], stage[2])
        else:
            raise ValueError("unknown stream stage %r" % (kind,))
    return b


def _replay_swap(b, perm, new_split):
    """One recorded swap stage on a CONCRETE array: recover the
    ``(kaxes, vaxes)`` the permutation was built from (``_do_swap``'s
    construction, inverted) and run the standard materialised swap —
    the streamed resolution and this replay therefore compile the SAME
    expression."""
    split = b.split
    kaxes = [p for p in perm[new_split:] if p < split]
    vaxes = [p - split for p in perm[:new_split] if p >= split]
    return b._do_swap(kaxes, vaxes, True)


def _materialize_base(source):
    from bolt_tpu.parallel.sharding import key_sharding
    from bolt_tpu.tpu.array import BoltArrayTPU
    shape = source.shape
    sharding = key_sharding(source.mesh, shape, source.split)
    t0 = _clock()
    if source.kind == "callback":
        def produce(index):
            block = np.asarray(source.produce(index), dtype=source.dtype)
            want = tuple(len(range(*s.indices(nn)))
                         for s, nn in zip(index, shape))
            if block.shape != want:
                raise ValueError(
                    "fromcallback callback returned shape %s for index %s "
                    "(expected %s)" % (block.shape, index, want))
            return block
        data = jax.make_array_from_callback(shape, sharding, produce)
        _engine.record_transfer(
            prod(shape) * source.dtype.itemsize, _clock() - t0)
        return BoltArrayTPU(data, source.split, source.mesh)
    host = np.empty(shape, source.dtype)
    for lo, hi, block in source.slabs():
        host[lo:hi] = block
    if _multihost.is_multiprocess(source.mesh):
        # device_put cannot scatter a host array across processes —
        # each process's devices pick their own shards out of the
        # host-assembled copy (every process iterated the re-iterable
        # source itself, so each holds the full array).  Counted at the
        # LOCAL logical bytes (this process's distinct shard regions,
        # replicas deduped), matching the per-process transfer contract
        # of the streaming path.
        t0 = _clock()
        data = jax.make_array_from_callback(shape, sharding,
                                            lambda idx: host[idx])
        seen = set()
        local = 0
        for idx in sharding.addressable_devices_indices_map(
                tuple(shape)).values():
            box = tuple(s.indices(n)[:2] for s, n in zip(idx, shape))
            if box not in seen:
                seen.add(box)
                local += prod([b - a for a, b in box])
        _engine.record_transfer(local * source.dtype.itemsize,
                                _clock() - t0)
        return BoltArrayTPU(data, source.split, source.mesh)
    data = transfer(host, sharding)
    return BoltArrayTPU(data, source.split, source.mesh)


# ---------------------------------------------------------------------
# the two-phase shuffle (ISSUE 18): streamed swap resolution
# ---------------------------------------------------------------------

def _shuffle_fingerprint(source, pre_stages, perm, new_split, out_block):
    """Identity of one streamed-swap resolution for spill-manifest
    matching — same discipline as :func:`_run_fingerprint`: geometry +
    slab plan + the PRE-swap stage chain (callables by bytecode) + the
    permutation itself, so a resume never adopts buckets cut by a
    different pipeline."""
    from bolt_tpu.utils import code_token
    stages = "|".join(_stage_token(s) for s in pre_stages)
    return ("bolt-stream-spill-v1",
            "x".join(str(s) for s in source.shape), int(source.split),
            str(source.dtype), int(source.slab), str(source.kind),
            code_token(source.produce) if source.produce is not None
            else "", stages, repr(tuple(perm)), int(new_split),
            int(out_block))


def _bucket_host(part, lo, hi):
    """Host copy of rows ``[lo, hi)`` of a (possibly sharded) device
    array — assembled from ADDRESSABLE shards only, so on a pod this is
    exactly the rows this process owns under the output key sharding
    (the spill files never carry another host's data)."""
    out = np.empty((hi - lo,) + tuple(part.shape[1:]), part.dtype)
    for s in part.addressable_shards:
        idx = s.index
        slo, shi, _ = idx[0].indices(part.shape[0])
        a, b = max(slo, lo), min(shi, hi)
        if a >= b:
            continue
        data = np.asarray(s.data)
        out[(slice(a - lo, b - lo),) + tuple(idx[1:])] = \
            data[a - slo:b - slo]
    return out


def _owned_buckets(part, out_block):
    """Global bucket indices whose rows this process holds in ``part``
    (sorted; single-process: all of them)."""
    owned = set()
    n = part.shape[0]
    for s in part.addressable_shards:
        slo, shi, _ = s.index[0].indices(n)
        owned.update(range(slo // out_block, -(-shi // out_block)))
    return sorted(owned)


def _resolve_one_swap(source):
    """Resolve the FIRST recorded swap of ``source`` via the two-phase
    streaming shuffle (module docstring of
    ``bolt_tpu.parallel.shuffle``): phase 1 streams input slabs through
    the uploader pool and one re-bucket program each (all-to-all on
    pods), phase 2 either concatenates RESIDENT parts into the swapped
    array (post-swap stages replayed concretely) or returns a fresh
    stream source over SPILLED bucket files carrying the post-swap
    stages lazily.  Bit-identical to the materialised swap either way —
    the re-bucket program traces the same transpose and the same stage
    bodies."""
    from bolt_tpu import checkpoint as _ckptlib
    from bolt_tpu.parallel import shuffle as _shuffle
    from bolt_tpu.tpu.array import BoltArrayTPU
    from bolt_tpu.utils import chain_retry_step

    cut = next(k for k, s in enumerate(source.stages)
               if s[0] == "swap")
    pre = source.stages[:cut]
    _, perm, new_split = source.stages[cut]
    post = source.stages[cut + 1:]
    base = StreamSource(source.kind, source.produce, source.blocks,
                        source.shape, source.split, source.dtype,
                        source.mesh, source.slab, pre,
                        ckpt=source.ckpt, codec=source.codec)
    base._consumed = source._consumed
    st = result_state(base)
    mesh = source.mesh
    split = source.split
    spill_dir, _ = spill_scope()
    plan = _shuffle.plan_shuffle(st.shape, st.dtype, st.split, perm,
                                 new_split, mesh, base.slab,
                                 swap_budget(), spill_dir)
    if not plan.resident and spill_dir is None:
        raise RuntimeError(
            "streamed swap: the re-keyed working set (%.1f MiB) "
            "exceeds the resident budget (%.1f MiB) and no spill "
            "directory is configured — wrap the run in "
            "bolt_tpu.stream.spill(dir) (or raise the budget); "
            "analysis.check forecasts this as BLT017"
            % (plan.total_bytes / 2**20, (plan.budget or 0) / 2**20))

    codec_obj = resolve_codec(base)     # lossless or None (gated at
    delta_ok = split < len(source.shape)  # swap_stage record time)
    nretry = retry_limit()
    depth = prefetch_depth()
    nwork = pool_size(base)
    mspec = None
    if _multihost.mesh_process_count(mesh) > 1:
        err = _multihost.slab_divisibility_error(
            mesh, source.shape, split,
            base.slab_ranges() if base.kind == "callback" else [])
        if err is not None:
            raise ValueError(err)       # BLT012 — check() forecasts it
        err = _multihost.sidecar_codec_error(codec_obj, mesh)
        if err is not None:
            raise ValueError(err)
        mspec = _multihost.local_slab_spec(base)
        if not plan.resident:
            # pod spill is refused, not attempted: phase 1 spills each
            # bucket whole on the one process that owns its rows, but
            # re-streaming those buckets as pod slabs needs every slab
            # SPLIT across processes (the BLT012 divisibility
            # contract) — two ownership models that cannot both hold.
            raise RuntimeError(
                "streamed swap: the re-keyed working set (%.1f MiB) "
                "exceeds the resident budget (%.1f MiB) and disk "
                "spill is single-process only — on a multi-process "
                "mesh raise the arbiter budget so the buckets stay "
                "resident, or materialise first (toarray) and swap "
                "in memory; analysis.check forecasts this as BLT017"
                % (plan.total_bytes / 2**20, (plan.budget or 0) / 2**20))

    # spill-manifest resume (fingerprinted like stream checkpoints):
    # slabs whose every bucket landed are skipped — their files are
    # complete by the atomic-rename + mark-after-buckets discipline.
    # Pod runs re-run phase 1 whole: per-process manifests can disagree
    # after an asymmetric kill, and a disagreeing slab schedule would
    # cross the all-to-all rendezvous (overwrites are atomic, so the
    # re-run is correct, just unskipped).
    fp = _shuffle_fingerprint(base, pre, perm, new_split,
                              plan.out_block)
    done = set()
    if not plan.resident and base.kind == "callback" and mspec is None:
        done = _ckptlib.spill_manifest(spill_dir, fp)
        if done:
            _engine.record_stream_resume()
            _obs.event("stream.spill_resume", slabs=len(done))

    ranges = base.slab_ranges() if base.kind == "callback" else None
    jobs = None
    if ranges is not None:
        jobs = [(g, lo, hi) for g, (lo, hi) in enumerate(ranges)
                if g not in done]
    wire_item = (codec_obj.wire_dtype(source.dtype).itemsize
                 if codec_obj is not None else source.dtype.itemsize)
    tenant_tag = _engine.current_tenant()
    lease = _tenant_lease()
    ring = depth + nwork
    permits = threading.Semaphore(ring)
    stop = threading.Event()
    rsq = _Reseq()
    jobq = queue.Queue()
    run_sp = _obs.begin("stream.shuffle", resident=plan.resident,
                        slabs=plan.nslabs, buckets=plan.nbuckets,
                        out_block=plan.out_block,
                        alltoall_bytes=plan.alltoall_bytes)

    def _encode_upload(block, slab_shape, axis0_off):
        side = ()
        if codec_obj is None:
            payload = block
        else:
            payload, side = _encode_slab(codec_obj, block, delta_ok)
        if mspec is None:
            buf = _upload_slab(payload, mesh, split)
        else:
            buf = _upload_slab_mh(payload, mesh, split, slab_shape,
                                  axis0_off)
        if side:
            buf = (buf,) + tuple(transfer(np.asarray(s)) for s in side)
        return buf, int(payload.nbytes)

    def _retry_or_raise(g, attempt, prev, exc, what):
        allowed = attempt < nretry and not stop.is_set()
        if allowed:
            _engine.record_stream_retry()
            _obs.event("stream.retry", slab=g, attempt=attempt + 1,
                       error=type(exc).__name__)
        return chain_retry_step(exc, prev, attempt, allowed,
                                "%s %d" % (what, g),
                                "stream.retries / BOLT_STREAM_RETRIES")

    def dispenser():
        try:
            for j, (g, lo, hi) in enumerate(jobs):
                if not _acquire(permits, stop):
                    return
                if lease is not None:
                    nrec = hi - lo
                    if mspec is not None:
                        llo, lhi = mspec.local_range(lo, hi)
                        nrec = lhi - llo
                    if not lease.acquire(
                            nrec * prod(source.shape[1:]) * wire_item,
                            stop=stop):
                        return
                jobq.put((j, g, lo, hi))
            rsq.finish(len(jobs))
        except BaseException as exc:        # noqa: BLE001 — re-raised
            rsq.fault(exc)                  # in the consumer
        finally:
            for _ in range(nwork):
                jobq.put(None)

    def worker(wid):
        try:
            with _engine.tenant(tenant_tag):
                while True:
                    job = jobq.get()
                    if job is None or stop.is_set():
                        return
                    j, g, lo, hi = job
                    attempt = 0
                    prev = None
                    while True:
                        sp = _obs.begin("stream.ingest", parent=run_sp,
                                        slab=g, worker=wid,
                                        attempt=attempt)
                        t0 = _clock()
                        try:
                            if mspec is None:
                                block = base.produce_slab(lo, hi)
                                buf, bnb = _encode_upload(
                                    block, block.shape, 0)
                            else:
                                llo, lhi = mspec.local_range(lo, hi)
                                block = base.produce_slab(llo, lhi)
                                buf, bnb = _encode_upload(
                                    block, mspec.slab_shape(lo, hi),
                                    llo - lo)
                            tsec = _clock() - t0
                            if sp is not None:
                                sp.set(bytes=bnb, lo=lo, hi=hi)
                        except BaseException as exc:  # noqa: BLE001
                            _obs.end(sp, error=type(exc).__name__)
                            prev = _retry_or_raise(g, attempt, prev, exc,
                                                   "shuffle slab")
                            attempt += 1
                            continue
                        _obs.end(sp)
                        break
                    del block
                    rsq.put(j, (g, buf, bnb, tsec))
        except BaseException as exc:        # noqa: BLE001
            rsq.fault(exc)

    def prefetch():
        # iterator sources: ONE sequential produce+upload thread; a
        # one-shot iterable cannot resume, so `done` is always empty
        j = 0
        try:
            with _engine.tenant(tenant_tag):
                for g, (lo, hi, block) in enumerate(
                        iter_record_blocks_indexed(base)):
                    if stop.is_set():
                        return
                    if not _acquire(permits, stop):
                        return
                    sp = _obs.begin("stream.ingest", parent=run_sp,
                                    slab=g)
                    t0 = _clock()
                    try:
                        if lease is not None and not lease.acquire(
                                int(block.size) * wire_item, stop=stop):
                            return
                        attempt = 0
                        prev = None
                        while True:
                            try:
                                buf, bnb = _encode_upload(
                                    block, block.shape, 0)
                                break
                            except BaseException as exc:  # noqa: BLE001
                                prev = _retry_or_raise(
                                    g, attempt, prev, exc,
                                    "shuffle slab")
                                attempt += 1
                        tsec = _clock() - t0
                        if sp is not None:
                            sp.set(bytes=bnb, lo=lo, hi=hi)
                    finally:
                        _obs.end(sp)
                    del block
                    rsq.put(j, (g, buf, bnb, tsec))
                    j += 1
                rsq.finish(j)
        except BaseException as exc:        # noqa: BLE001
            rsq.fault(exc)

    def iter_record_blocks_indexed(src):
        for lo, hi, block in src.slabs():
            yield lo, hi, block

    if base.kind == "callback":
        lead = threading.Thread(target=dispenser,
                                name="bolt-shuffle-prefetch",
                                daemon=True)
        pool = [threading.Thread(target=worker, args=(w,),
                                 name="bolt-shuffle-upload-%d" % w,
                                 daemon=True)
                for w in range(nwork)]
        threads = [lead] + pool
        ingesters = pool
    else:
        lead = threading.Thread(target=prefetch,
                                name="bolt-shuffle-prefetch",
                                daemon=True)
        threads = [lead]
        ingesters = threads

    def _spill_part(part, g):
        """Extract and persist every LOCALLY-OWNED bucket of slab
        ``g``'s transposed part (atomic files; the slab is marked
        complete only after its last bucket lands — the kill -9
        resume point)."""
        for bkt in _owned_buckets(part, plan.out_block):
            lo = bkt * plan.out_block
            hi = min(lo + plan.out_block, plan.out_shape[0])
            block = _bucket_host(part, lo, hi)
            attempt = 0
            prev = None
            while True:
                ssp = _obs.begin("stream.spill", slab=g, bucket=bkt)
                try:
                    _chaos.hit("stream.spill")
                    nb = _ckptlib.spill_save(spill_dir, fp, g, bkt,
                                             block, lo)
                    if ssp is not None:
                        ssp.set(bytes=nb)
                    _obs.end(ssp)
                    _engine.record_spill(nb)
                    break
                except BaseException as exc:  # noqa: BLE001
                    _obs.end(ssp, error=type(exc).__name__)
                    prev = _retry_or_raise(g, attempt, prev, exc,
                                           "spill slab")
                    attempt += 1
        _ckptlib.spill_slab_done(spill_dir, fp, g)

    t_start = _clock()
    moved = 0
    parts = []
    pshapes = []
    for th in threads:
        th.start()
    if mspec is not None:
        _podwatch.pod_enter()
    ready_done = False
    try:
        while True:
            got = rsq.next(threads, workers=ingesters)
            if got is None:
                break
            if mspec is not None and not ready_done:
                _podwatch.ready_rendezvous()
                ready_done = True
            j, (g, buf, bnb, tsec) = got
            wshape = (buf[0].shape if isinstance(buf, tuple)
                      else buf.shape)
            csp = _obs.begin("stream.compute", slab=g, shuffle=True)
            attempt = 0
            prev = None
            try:
                while True:
                    try:
                        # the chaos seam fires BEFORE the dispatch, so
                        # an injected raise leaves the donated buffer
                        # intact — the in-place retry (same fence as
                        # ingest retries) re-dispatches it verbatim
                        _chaos.hit("stream.shuffle")
                        prog = _shuffle.rebucket_program(
                            plan, pre, mesh, codec_obj, source.dtype,
                            wshape, delta_ok)
                        with warnings.catch_warnings():
                            # CPU dev meshes have no donation: the
                            # per-slab "donated buffers were not
                            # usable" warning is expected noise there
                            warnings.filterwarnings(
                                "ignore", message="Some donated "
                                "buffers were not usable")
                            part = prog(buf)
                        _pod_sync(part, mspec is not None,
                                  "shuffle re-bucket", slab=g)
                        break
                    except _podwatch.PeerLostError:
                        raise
                    except BaseException as exc:  # noqa: BLE001
                        prev = _retry_or_raise(g, attempt, prev, exc,
                                               "shuffle dispatch")
                        attempt += 1
            finally:
                _obs.end(csp)
            del buf, got
            moved += int(prod(part.shape)
                         * np.dtype(part.dtype).itemsize)
            if plan.resident:
                parts.append((g, part))
                pshapes.append(tuple(part.shape))
            else:
                _spill_part(part, g)
                del part
            permits.release()
            if lease is not None:
                lease.release(bnb)
    finally:
        stop.set()
        for _ in range(len(threads)):
            jobq.put(None)
        for th in threads:
            th.join()
        rsq.drain()
        if mspec is not None:
            _podwatch.pod_exit()
        if lease is not None:
            lease.close()
        _engine.record_shuffle(moved, _clock() - t_start)
        if run_sp is not None:
            run_sp.set(bytes=moved)
        _obs.end(run_sp)

    if plan.resident:
        if not parts:
            raise RuntimeError(
                "streamed swap produced no slabs (empty source?) — "
                "the materialised path owns empty-input rules")
        # slab order was re-sequenced, but `done`-skips never happen
        # resident (no manifest) — parts arrive in slab order already
        parts = [p for _, p in sorted(parts, key=lambda t: t[0])]
        prog = _shuffle.concat_program(plan, tuple(pshapes), mesh)
        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            data = prog(*parts)
        _pod_sync(data, mspec is not None, "shuffle concat")
        del parts
        b = BoltArrayTPU(data, new_split, mesh)
        return _replay_stages(b, post)

    # SPILLED: phase 2 is a fresh callback source over the bucket
    # files — it streams through the SAME slab-program machinery as
    # any other source (execute/materialize/retries/arbiter/resume all
    # inherited), with the post-swap stages riding lazily
    nslabs = plan.nslabs
    out_shape = plan.out_shape
    out_block = plan.out_block
    j0 = plan.j0
    out_n = out_shape[0]

    def produce(index):
        lo, hi, _ = index[0].indices(out_n)
        chunks = []
        for bkt in range(lo // out_block, -(-hi // out_block)):
            pieces = [_ckptlib.spill_load(spill_dir, fp, g, bkt)
                      for g in range(nslabs)]
            blk = np.concatenate([p[0] for p in pieces], axis=j0)
            chunks.append((pieces[0][1], blk))
        full = np.concatenate([c[1] for c in chunks], axis=0)
        row0 = chunks[0][0]
        out = full[lo - row0:hi - row0]
        return out[(slice(None),) + tuple(index[1:])]

    src2 = StreamSource("callback", produce, None, out_shape, new_split,
                        st.dtype, mesh, out_block, post,
                        ckpt=source.ckpt, codec=None)
    return BoltArrayTPU._streamed(src2)
