"""Deterministic chaos-injection registry: the blessed fault seams.

Fault tolerance that is only exercised by real outages is fault
tolerance that does not work.  This module gives the streaming executor,
the checkpoint layer and the serving scheduler NAMED fault points —
``chaos.hit("stream.upload")`` at the top of the uploader hot path,
``"stream.dispatch"`` / ``"stream.fold"`` in the consumer,
``"stream.checkpoint"`` in the checkpoint writer, ``"checkpoint.meta"``
between a pod abort's state write and its meta rename, and the POD
seams (ISSUE 11): ``"multihost.barrier"``, ``"multihost.collective"``
(every pod slab dispatch) and ``"podwatch.heartbeat"`` (each liveness
beat — ``kill`` here is the cleanest deterministic pod-member
preemption) — and a registry that trips a chosen one
deterministically:

>>> from bolt_tpu import _chaos as chaos
>>> chaos.inject("stream.upload", nth=3)          # 3rd upload raises
>>> chaos.inject("stream.upload", nth=3, exc=IOError("link down"))
>>> chaos.inject("stream.upload", nth=3, action="kill")   # SIGKILL self

``nth`` counts hits process-wide (1-based); ``times`` bounds how many
hits trip once armed (default 1 — a retried upload then succeeds,
which is exactly how a flaky storage fetch behaves; ``times=None``
keeps failing forever, the retries-exhausted shape).  ``action="kill"``
delivers ``SIGKILL`` to the OWN process — the preemption test: nothing
runs after it, no ``finally`` blocks, no atexit — which is why the
checkpoint layer's atomic-rename discipline matters.

The env form arms a point before any code runs, for subprocess tests::

    BOLT_CHAOS="stream.upload:3:kill"       python job.py
    BOLT_CHAOS="stream.upload:3:raise"      python job.py
    BOLT_CHAOS="stream.upload:3:raise:disk gone" python job.py

Disarmed cost is one module-global check per seam.  Lint rule BLT109
keeps ``os.kill``/``signal`` fault injection in THIS file (and
tests/scripts) only — production code must reach faults through these
seams, never raise its own signals.

Stdlib-only: importable by the checkpoint layer and by scripts with no
jax in sight.
"""

import os
import signal
import sys


def _lockdep():
    """The lock-inventory module (bolt_tpu/_lockdep.py), loaded by path
    under its canonical name when the package is not imported — this
    module must stay loadable with no bolt_tpu (and no jax) in sight,
    and a later package import must adopt the SAME witness instance."""
    mod = sys.modules.get("bolt_tpu._lockdep")
    if mod is None:
        import importlib.util
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "_lockdep.py")
        spec = importlib.util.spec_from_file_location(
            "bolt_tpu._lockdep", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules["bolt_tpu._lockdep"] = mod
        spec.loader.exec_module(mod)
    return mod


_LOCK = _lockdep().lock("chaos.registry")
_POINTS = {}            # name -> _Spec
_ARMED = False          # the one hot-path check

# every registered seam in the package — scripts/chaos_run.py --matrix
# sweeps this list x {raise, kill} and asserts recovery or a pointed
# error for each; adding a chaos.hit() call site means adding it here
SEAMS = (
    "stream.encode",          # codec slab encode on an uploader worker
    "stream.upload",          # uploader-pool / prefetch ingest hot path
    "stream.dispatch",        # consumer, before each slab dispatch
    "stream.fold",            # the final pairwise fold
    "stream.shuffle",         # before each shuffle re-bucket dispatch
    "stream.spill",           # before each spilled-bucket write
    "stream.checkpoint",      # checkpoint.stream_save entry
    "checkpoint.meta",        # between state write and meta rename
    "checkpoint.corrupt",     # flips bytes in a just-written state file
    "multihost.barrier",      # every named cross-process rendezvous
    "multihost.collective",   # every pod slab dispatch
    "podwatch.heartbeat",     # each liveness beat (kill = preemption)
    "supervisor.elect",       # top of every supervised recovery attempt
    "supervisor.rejoin",      # the rejoin-door handler
)


class ChaosError(RuntimeError):
    """The default exception an armed fault point raises."""


class _Spec:
    __slots__ = ("point", "nth", "exc", "action", "times", "hits",
                 "trips")

    def __init__(self, point, nth, exc, action, times):
        self.point = point
        self.nth = max(1, int(nth))
        self.exc = exc
        self.action = action
        self.times = times          # None = unbounded
        self.hits = 0
        self.trips = 0


def inject(point, nth=1, exc=None, action="raise", times=1):
    """Arm fault point ``point`` to trip on its ``nth`` hit (1-based,
    counted process-wide across threads).

    ``action="raise"`` raises ``exc`` (default a :class:`ChaosError`
    naming the point) INSIDE the instrumented seam — the thread-failure
    variant, exercising the retry/abort paths; ``action="kill"``
    delivers ``SIGKILL`` to this process — the preemption variant,
    exercising checkpoint resume.  ``times`` bounds consecutive trips
    once armed (``None`` = every hit from ``nth`` on)."""
    if action not in ("raise", "kill"):
        raise ValueError("chaos action must be 'raise' or 'kill', got %r"
                         % (action,))
    global _ARMED
    with _LOCK:
        _POINTS[point] = _Spec(point, nth, exc, action, times)
        _ARMED = True
    return _POINTS[point]


def clear(point=None):
    """Disarm one fault point (or all of them)."""
    global _ARMED
    with _LOCK:
        if point is None:
            _POINTS.clear()
        else:
            _POINTS.pop(point, None)
        _ARMED = bool(_POINTS)


def active():
    """Names of the armed fault points."""
    with _LOCK:
        return sorted(_POINTS)


def stats(point):
    """``(hits, trips)`` for one point (``(0, 0)`` when never armed)."""
    with _LOCK:
        spec = _POINTS.get(point)
        return (spec.hits, spec.trips) if spec is not None else (0, 0)


def hit(point):
    """The seam call: count one hit of ``point`` and trip the armed
    fault when due.  ONE module-global check when nothing is armed —
    the production cost of the whole registry."""
    if not _ARMED:
        return
    with _LOCK:
        spec = _POINTS.get(point)
        if spec is None:
            return
        spec.hits += 1
        due = spec.hits >= spec.nth and (
            spec.times is None or spec.trips < spec.times)
        if not due:
            return
        spec.trips += 1
        action, exc = spec.action, spec.exc
    if action == "kill":
        # the preemption: no unwinding, no finally, no atexit — the
        # process is simply gone, like a kill -9'd or preempted worker
        os.kill(os.getpid(), signal.SIGKILL)
    raise exc if exc is not None else ChaosError(
        "chaos: injected fault at %r (hit %d)" % (point, spec.hits))


def _load_env():
    """Arm a fault point from ``BOLT_CHAOS=point:nth:action[:message]``
    — the subprocess form (the parent sets the env, the child trips it
    with no code changes)."""
    raw = os.environ.get("BOLT_CHAOS")
    if not raw:
        return
    parts = raw.split(":", 3)
    if len(parts) < 2:
        raise ValueError(
            "BOLT_CHAOS must be 'point:nth[:action[:message]]', got %r"
            % raw)
    point, nth = parts[0], int(parts[1])
    action = parts[2] if len(parts) > 2 and parts[2] else "raise"
    exc = ChaosError(parts[3]) if len(parts) > 3 else None
    inject(point, nth=nth, exc=exc, action=action)


_load_env()
