"""Abstract cross-backend array contract.

Reference parity: ``bolt/base.py :: BoltArray`` — the contract both backends
implement (``mode``, ``shape``, ``dtype``, ``map/filter/reduce``,
``toarray``, conversions, ``__repr__``).  Citations are symbol-level; see
SURVEY.md §0.
"""

from abc import ABCMeta, abstractmethod


class HostFallbackWarning(UserWarning):
    """A ``mode='tpu'`` functional op received a non-jax-traceable callable
    and is rerouting through the local (NumPy) oracle — a full
    device→host→device round-trip.  Semantics are preserved but throughput
    drops by orders of magnitude on real hardware; rewrite the callable with
    the jax-compatible numpy-API subset to stay on device (SURVEY §7 hard
    part 4's documented escape hatch).  Filter or ``error`` this category to
    locate (or forbid) fallback sites."""


class HBMPressureWarning(UserWarning):
    """An operation's estimated device-memory demand exceeds the ASSUMED
    accelerator memory (the device did not report its capacity, so the
    smallest-current-TPU default applies).  The op may still succeed on
    larger chips — set ``BOLT_HBM_BYTES`` (or
    ``bolt_tpu.tpu.array._HBM_LIMIT_OVERRIDE``) to your chip's HBM size
    to turn this into an accurate up-front ``MemoryError`` instead of a
    mid-program XLA OOM."""


class BoltArray(metaclass=ABCMeta):
    """An n-dimensional array whose axes split into *key axes* (the
    distributed / parallel domain) and *value axes* (the local block each
    unit of parallelism holds).

    Backends:

    * ``mode='local'`` — :class:`bolt_tpu.local.array.BoltArrayLocal`, a
      ``numpy.ndarray`` subclass; the semantic oracle.
    * ``mode='tpu'`` — :class:`bolt_tpu.tpu.array.BoltArrayTPU`, a sharded
      ``jax.Array`` over a ``jax.sharding.Mesh``; key axes map onto mesh
      axes, so the key/value split *is* the sharding spec.
    """

    _mode = None

    @property
    def mode(self):
        """Backend identifier: ``'local'`` or ``'tpu'``."""
        return self._mode

    @property
    @abstractmethod
    def shape(self):
        """Full logical shape, key axes leading."""

    @property
    @abstractmethod
    def dtype(self):
        """Element dtype."""

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def size(self):
        n = 1
        for s in self.shape:
            n *= int(s)
        return n

    @property
    @abstractmethod
    def _constructor(self):
        """The construction class for this backend (``ConstructLocal`` /
        ``ConstructTPU``)."""

    # ------------------------------------------------------------------
    # functional operators (reference: ``bolt/base.py`` abstract methods)
    # ------------------------------------------------------------------

    @abstractmethod
    def map(self, func, axis=(0,), value_shape=None, dtype=None, with_keys=False):
        """Apply ``func`` to the value block at every key."""

    @abstractmethod
    def filter(self, func, axis=(0,), sort=False):
        """Keep the records whose value block satisfies ``func``; the
        surviving records are re-keyed to a flat ``(n,)`` key space."""

    @abstractmethod
    def reduce(self, func, axis=(0,), keepdims=False):
        """Combine all value blocks pairwise with the associative binary
        ``func``."""

    # ------------------------------------------------------------------
    # conversions
    # ------------------------------------------------------------------

    @abstractmethod
    def toarray(self, out=None):
        """Materialise as a host ``numpy.ndarray`` in key order; with
        ``out=`` (a writable shape/dtype-matched array, e.g. a memmap)
        the gather writes into the caller's buffer instead of
        allocating."""

    @abstractmethod
    def iter_shards(self):
        """Yield ``(index, block)`` host copies per locally-addressable
        shard — the assembly-free collect (one whole-array block on the
        local backend)."""

    @abstractmethod
    def tolocal(self):
        """Convert to the ``mode='local'`` backend."""

    @staticmethod
    def _check_out(out, shape, dtype):
        """Shared ``out=`` validation for :meth:`toarray` — one
        implementation so the backends' messages cannot drift."""
        import numpy as np
        if tuple(out.shape) != tuple(shape):
            raise ValueError("out has shape %s, expected %s"
                             % (tuple(out.shape), tuple(shape)))
        if np.dtype(out.dtype) != np.dtype(dtype):
            raise ValueError(
                "out has dtype %s, expected %s (toarray does not cast)"
                % (out.dtype, np.dtype(dtype)))
        return out

    def totpu(self, context=None, axis=(0,)):
        """Convert to the ``mode='tpu'`` backend, distributing ``axis`` as
        key axes over the mesh ``context``.

        Replaces the reference's ``tospark(sc, axis)`` in the same structural
        slot (reference: ``bolt/local/array.py :: BoltArrayLocal.tospark``).
        """
        from bolt_tpu.tpu.construct import ConstructTPU
        return ConstructTPU.array(self.toarray(), context=context, axis=axis)

    def __repr__(self):
        s = "BoltArray\n"
        s += "mode: %s\n" % self.mode
        s += "shape: %s\n" % str(tuple(self.shape))
        return s
